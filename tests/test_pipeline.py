"""Pipeline parallelism: equivalence with the single-device accumulated step.

The pipelined schedule (M microbatches through S stages, GPipe bubble) must
produce the SAME loss/gradients/updated params as the single-device train
step with gradient-accumulation factor M — PP changes where layers run, not
the math.

Core file of the split pipeline suite (see tests/_pipeline_common.py):
schedules, config rejection, state placement, grad clipping. In-stage
ZeRO lives in test_pipeline_zero.py; TP/EP compositions in
test_pipeline_comp.py; MoE in test_pipeline_moe.py; dropout in
test_pipeline_dropout.py; in-stage seq in test_pipeline_seq.py.
"""

from __future__ import annotations

import jax
import pytest

from _pipeline_common import (  # noqa: F401  (setup is a fixture)
    assert_matches_ref,
    assert_params_close,
    setup,
)
from pytorch_distributed_tpu.config import MeshConfig, TrainConfig
from pytorch_distributed_tpu.parallel import make_mesh
from pytorch_distributed_tpu.parallel.pipeline import (
    make_pipeline_train_step,
    shard_pipeline_state,
)
from pytorch_distributed_tpu.train.optim import make_optimizer
from pytorch_distributed_tpu.train.state import init_train_state
from pytorch_distributed_tpu.train.trainer import make_train_step
from pytorch_distributed_tpu.utils.prng import domain_key

# Heavy tier: long-compiling file; excluded from `pytest -m quick`
# (see tests/conftest.py + pyproject markers).
# Heavy tier AND slow tier: these compile-bound equivalence batteries
# dominate suite wall-clock; the tier-1 CI command (ROADMAP.md) runs
# -m 'not slow' to stay inside its time budget — plain `pytest` and
# nightly runs still execute them.
pytestmark = [pytest.mark.full, pytest.mark.slow]


@pytest.mark.parametrize("pipe,data", [(2, 1), (4, 1), (2, 2), (4, 2)])
def test_pipeline_matches_single_device(setup, pipe, data):
    cfg, model, tx = setup["cfg"], setup["model"], setup["tx"]
    mcfg = MeshConfig(pipe=pipe, data=data, strategy="no_shard")
    mesh = make_mesh(mcfg)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    state, _ = shard_pipeline_state(state, mesh, mcfg)
    step = make_pipeline_train_step(model, cfg, tx, mesh, mcfg, state)
    new_state, metrics = step(state, setup["batch"], jax.random.key(0))
    assert_matches_ref(setup, new_state, metrics)


def test_pipeline_rejects_bad_configs(setup):
    cfg, model, tx = setup["cfg"], setup["model"], setup["tx"]
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    mcfg2 = MeshConfig(pipe=3, strategy="no_shard")
    with pytest.raises(ValueError, match="divisible"):
        make_pipeline_train_step(
            model, cfg, tx, make_mesh(mcfg2), mcfg2, state
        )


def test_pipeline_llama_default_pdrops_accepted_on_tp_mesh(eight_devices):
    """A hand-built llama ModelConfig keeps nonzero *_pdrop defaults but
    the family ignores dropout — the pipeline's in-stage-TP attention-
    dropout rejection must not fire for it (round-4 advisor finding)."""
    from _pipeline_common import build_case

    case = build_case("llama", with_ref=False)
    cfg = case["cfg"].replace(
        embd_pdrop=0.1, attn_pdrop=0.1, resid_pdrop=0.1
    )
    from pytorch_distributed_tpu.models import get_model

    model = get_model(cfg)
    state = init_train_state(
        model.init(domain_key(42, "init"), cfg), case["tx"]
    )
    mcfg = MeshConfig(pipe=2, tensor=2, strategy="no_shard")
    mesh = make_mesh(mcfg)
    # Build-time acceptance is the contract under test; no step run.
    make_pipeline_train_step(model, cfg, case["tx"], mesh, mcfg, state)


def test_pipeline_zero2_shards_opt_state_not_params(setup):
    """Under pipe x shard_grad_op the optimizer moments shard over fsdp
    while params stay replicated over it (ZeRO-2's defining memory shape)."""
    cfg, model, tx = setup["cfg"], setup["model"], setup["tx"]
    mcfg = MeshConfig(pipe=2, fsdp=2, strategy="shard_grad_op")
    from pytorch_distributed_tpu.parallel.pipeline import (
        pipeline_state_specs,
    )

    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    specs = pipeline_state_specs(state, mcfg)
    import jax.tree_util as jtu
    from jax.sharding import PartitionSpec as P

    def has_fsdp(spec):
        return any(
            e == "fsdp" or (isinstance(e, tuple) and "fsdp" in e)
            for e in spec
        )

    assert not any(
        has_fsdp(s)
        for s in jtu.tree_leaves(
            specs.params, is_leaf=lambda x: isinstance(x, P)
        )
    )
    assert any(
        has_fsdp(s)
        for s in jtu.tree_leaves(
            specs.opt_state, is_leaf=lambda x: isinstance(x, P)
        )
    )


def test_pipeline_fsdp_actually_shards_state(setup):
    """Under pipe x fsdp full_shard each device holds 1/(pipe*fsdp) of the
    block params and 1/fsdp of the embedding table."""
    import numpy as np

    cfg, model, tx = setup["cfg"], setup["model"], setup["tx"]
    mcfg = MeshConfig(pipe=2, fsdp=2, data=2, strategy="full_shard")
    mesh = make_mesh(mcfg)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    state, _ = shard_pipeline_state(state, mesh, mcfg)
    wte = state.params["wte"]  # [V, E] -> E over fsdp
    assert {s.data.shape[1] for s in wte.addressable_shards} == {
        cfg.n_embd // 2
    }
    leaf = jax.tree.leaves(state.params["blocks"])[0]
    shard = leaf.addressable_shards[0].data
    assert shard.shape[0] == cfg.n_layer // 2  # pipe slice of the stack
    assert np.prod(shard.shape) == np.prod(leaf.shape) // 4  # + fsdp dim


@pytest.mark.parametrize(
    "pipe,data,fsdp,strategy,schedule",
    [
        (2, 2, 1, "no_shard", "gpipe"),
        (2, 1, 2, "full_shard", "gpipe"),
        (2, 2, 1, "no_shard", "1f1b"),
    ],
)
def test_pipeline_grad_clip_matches_single_device(
    setup, pipe, data, fsdp, strategy, schedule
):
    """Global-norm clipping on the pipeline path (VERDICT r3 weak #1): the
    step clips against the pipe/fsdp-aware psum'd global norm, so the
    clipped update must match the single-device optax.clip_by_global_norm
    step exactly. The threshold is set BELOW the observed norm so the clip
    provably engages."""
    cfg, model = setup["cfg"], setup["model"]
    clip = 0.5 * setup["ref_gnorm"]
    tcfg = TrainConfig(
        global_batch_size=24, micro_batch_size=8, num_steps=1,
        learning_rate=1e-3, grad_clip_norm=clip,
    )
    tx_ref = make_optimizer(tcfg)  # optax clip element included
    state0 = init_train_state(model.init(domain_key(42, "init"), cfg), tx_ref)
    ref_state, ref_metrics = make_train_step(
        model, cfg, tx_ref, donate=False
    )(state0, setup["batch"], jax.random.key(0))
    assert float(ref_metrics["grad_norm"]) > clip  # clip engaged

    mcfg = MeshConfig(
        pipe=pipe, data=data, fsdp=fsdp, strategy=strategy,
        pipe_schedule=schedule,
    )
    mesh = make_mesh(mcfg)
    tx = make_optimizer(tcfg, with_clip=False)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    state, _ = shard_pipeline_state(state, mesh, mcfg)
    step = make_pipeline_train_step(
        model, cfg, tx, mesh, mcfg, state, tcfg,
        schedule=schedule, grad_clip_norm=clip,
    )
    new_state, metrics = step(state, setup["batch"], jax.random.key(0))
    assert float(metrics["grad_norm"]) == pytest.approx(
        float(ref_metrics["grad_norm"]), abs=1e-4
    )
    assert_params_close(ref_state.params, new_state.params)


def test_pipeline_clip_requires_clip_free_tx(setup):
    """train_cfg.grad_clip_norm WITHOUT the explicit kwarg is rejected:
    the caller's tx presumably embeds optax's clip, which would apply a
    stage-local norm inside shard_map."""
    cfg, model, tx = setup["cfg"], setup["model"], setup["tx"]
    mcfg = MeshConfig(pipe=2, strategy="no_shard")
    mesh = make_mesh(mcfg)
    tcfg = TrainConfig(
        global_batch_size=24, micro_batch_size=8, num_steps=1,
        grad_clip_norm=1.0,
    )
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    with pytest.raises(ValueError, match="with_clip=False"):
        make_pipeline_train_step(model, cfg, tx, mesh, mcfg, state, tcfg)


def test_pipeline_rejects_unknown_schedule(setup):
    cfg, model, tx = setup["cfg"], setup["model"], setup["tx"]
    mcfg = MeshConfig(pipe=2, strategy="no_shard")
    mesh = make_mesh(mcfg)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    with pytest.raises(ValueError, match="schedule"):
        make_pipeline_train_step(
            model, cfg, tx, mesh, mcfg, state, schedule="zigzag"
        )
