import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.attention import naive_attention
from pytorch_distributed_tpu.ops.pallas_flash import flash_attention


def _qkv(b=2, t=64, h=4, hkv=None, d=16, seed=0, dtype=jnp.float32):
    hkv = h if hkv is None else hkv
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), dtype)
    k = jax.random.normal(ks[1], (b, t, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, t, hkv, d), dtype)
    return q, k, v


def test_flash_matches_naive_causal():
    q, k, v = _qkv()
    ref = naive_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_matches_naive_noncausal():
    q, k, v = _qkv(t=32)
    ref = naive_attention(q, k, v, causal=False)
    got = flash_attention(q, k, v, causal=False, block_q=8, block_k=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_gqa():
    q, k, v = _qkv(h=8, hkv=2)
    ref = naive_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_ragged_block_fallback():
    # T not divisible by requested block -> single-block fallback, still right.
    q, k, v = _qkv(t=48)
    ref = naive_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match_naive():
    q, k, v = _qkv(t=32)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=8, block_k=8) ** 2
        )

    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gn, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_decode_offset_alignment():
    """S > T (querying with a KV cache): last query attends to all keys,
    first query to the first S-T+1 keys."""
    b, h, d = 1, 2, 8
    q = jax.random.normal(jax.random.key(0), (b, 4, h, d))
    k = jax.random.normal(jax.random.key(1), (b, 12, h, d))
    v = jax.random.normal(jax.random.key(2), (b, 12, h, d))
    ref = naive_attention(q, k, v, causal=True)
    # Manual check for the first query row: softmax over first 9 keys only.
    scores = jnp.einsum("thd,shd->hts", q[0], k[0]) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    w = jax.nn.softmax(scores[:, 0, :9], axis=-1)
    manual = jnp.einsum("hs,shd->hd", w, v[0, :9])
    np.testing.assert_allclose(np.asarray(ref[0, 0]), np.asarray(manual), atol=1e-5)
