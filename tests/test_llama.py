import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.config import ModelConfig, model_config
from pytorch_distributed_tpu.models import get_model, llama
from pytorch_distributed_tpu.ops.rope import apply_rope, rope_angles


@pytest.fixture(scope="module")
def tiny_llama():
    return ModelConfig(
        family="llama",
        vocab_size=101,
        n_ctx=32,
        n_embd=32,
        n_layer=2,
        n_head=4,
        n_kv_head=2,
        activation_function="silu",
        embd_pdrop=0.0,
        attn_pdrop=0.0,
        resid_pdrop=0.0,
        dtype="float32",
    )


def test_llama_forward_shapes(tiny_llama):
    cfg = tiny_llama
    model = get_model(cfg)
    params = model.init(jax.random.key(0), cfg)
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    logits = model.apply(params, ids, cfg)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_llama_inner_dim_rule():
    # n_inner=None -> 8/3 rule rounded up to x256 for llama family.
    cfg = ModelConfig(family="llama", n_embd=4096, n_head=32)
    assert cfg.inner_dim == ((8 * 4096 // 3) + 255) // 256 * 256 == 11008
    # Presets carry explicit values (llama3-8b uses 14336).
    assert model_config("llama3-8b").inner_dim == 14336


def test_llama_causality(tiny_llama):
    cfg = tiny_llama
    model = get_model(cfg)
    params = model.init(jax.random.key(0), cfg)
    ids = np.asarray(
        jax.random.randint(jax.random.key(1), (1, 32), 0, cfg.vocab_size)
    )
    j = 20
    ids2 = ids.copy()
    ids2[0, j] = (ids2[0, j] + 1) % cfg.vocab_size
    l1 = np.asarray(model.apply(params, jnp.asarray(ids), cfg))
    l2 = np.asarray(model.apply(params, jnp.asarray(ids2), cfg))
    np.testing.assert_allclose(l1[0, :j], l2[0, :j], atol=1e-5)
    assert not np.allclose(l1[0, j:], l2[0, j:], atol=1e-5)


def test_rope_properties():
    """Rotation preserves norms and depends only on relative positions for
    dot products."""
    d = 16
    cos, sin = rope_angles(8, d, 10000.0)
    x = jax.random.normal(jax.random.key(0), (1, 8, 2, d))
    xr = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(xr), axis=-1),
        rtol=1e-5,
    )
    # Relative-position property: <R_i q, R_j k> == <R_{i+s} q, R_{j+s} k>.
    q = jax.random.normal(jax.random.key(1), (1, 1, 1, d))
    k = jax.random.normal(jax.random.key(2), (1, 1, 1, d))
    cos8, sin8 = rope_angles(8, d, 10000.0)

    def rot(x, pos):
        return apply_rope(x, cos8[pos : pos + 1], sin8[pos : pos + 1])

    dot_a = np.asarray(jnp.sum(rot(q, 2) * rot(k, 5)))
    dot_b = np.asarray(jnp.sum(rot(q, 0) * rot(k, 3)))
    np.testing.assert_allclose(dot_a, dot_b, rtol=1e-4)


def test_llama_flash_matches_naive(tiny_llama):
    cfg_naive = tiny_llama
    cfg_flash = tiny_llama.replace(attention_impl="flash")
    model = get_model(cfg_naive)
    params = model.init(jax.random.key(0), cfg_naive)
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg_naive.vocab_size)
    l_naive = model.apply(params, ids, cfg_naive)
    l_flash = model.apply(params, ids, cfg_flash)
    np.testing.assert_allclose(
        np.asarray(l_naive), np.asarray(l_flash), atol=2e-4
    )


def test_bad_attention_impl_rejected():
    with pytest.raises(ValueError):
        ModelConfig(attention_impl="warp9")
