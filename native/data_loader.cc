// Native (C++) kjj0 token-shard loader with background prefetch.
//
// The reference delegates its data path to torch's native stack (tensor
// allocation, pinned copies); this is the TPU-framework equivalent: a small
// C++ runtime component that owns file IO and batch assembly so the Python
// host loop spends its time dispatching XLA work, not gathering tokens.
//
// Format (reference data/data_loader.py:104-135, bin_format.py):
//   header: 256 little-endian int32 (magic 20240520, version 1, token_count)
//   payload: token_count uint16 tokens
//
// Semantics: the DISTRIBUTED lockstep stream (reference
// distributed_data_loader.py:16-24 worked example; distributed_loader.py):
//   - all ranks walk the same shard list in order;
//   - per batch, rank r takes tokens [pos + r*B*T, pos + (r+1)*B*T + 1)
//     (the +1 is the target shift) and reshapes to [B, T];
//   - every rank advances pos += world*B*T;
//   - shard switch when fewer than world*B*T + 1 tokens remain, so all
//     ranks switch in lockstep. world=1 gives the single-process stream.
//
// Concurrency: one producer thread assembles batches into a bounded ring
// (prefetch_depth deep); the consumer (Python via ctypes) pops fully-built
// int32 inputs/targets buffers. Assembly and page-cache faults overlap with
// accelerator compute.
//
// C ABI only — consumed through ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr int32_t kMagic = 20240520;
constexpr int32_t kVersion = 1;
constexpr int64_t kHeaderBytes = 256 * 4;

struct Shard {
  void* map = nullptr;
  size_t bytes = 0;
  const uint16_t* tokens = nullptr;
  int64_t count = 0;

  void close() {
    if (map != nullptr) {
      munmap(map, bytes);
      map = nullptr;
    }
    tokens = nullptr;
    count = 0;
  }
};

struct Loader {
  std::vector<std::string> paths;
  int64_t batch = 0, seq = 0;
  int rank = 0, world = 1;
  int depth = 2;

  // Sequential state (owned by the producer thread while it runs).
  size_t shard_idx = 0;
  Shard cur;
  int64_t pos = 0;

  // Prefetch ring.
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_can_push, cv_can_pop;
  std::deque<std::vector<int32_t>> ready;  // each: inputs||targets, 2*B*T
  bool exhausted = false;   // producer hit end of data
  bool stopping = false;    // consumer asked the producer to quit
  std::string error;        // sticky; set under mu by the producer

  ~Loader() { stop_worker(); cur.close(); }

  void stop_worker() {
    {
      std::lock_guard<std::mutex> g(mu);
      stopping = true;
    }
    cv_can_push.notify_all();
    cv_can_pop.notify_all();
    if (worker.joinable()) worker.join();
  }
};

bool open_shard(Loader* L, const std::string& path, std::string* err) {
  L->cur.close();
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) {
    *err = path + ": cannot open";
    return false;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < kHeaderBytes) {
    close(fd);
    *err = path + ": truncated header";
    return false;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) {
    *err = path + ": mmap failed";
    return false;
  }
  const int32_t* header = static_cast<const int32_t*>(map);
  int64_t count = header[2];
  if (header[0] != kMagic) {
    *err = path + ": bad magic " + std::to_string(header[0]) +
           ", expected " + std::to_string(kMagic);
    munmap(map, st.st_size);
    return false;
  }
  if (header[1] != kVersion) {
    *err = path + ": unsupported version " + std::to_string(header[1]);
    munmap(map, st.st_size);
    return false;
  }
  if (st.st_size < kHeaderBytes + count * 2) {
    *err = path + ": payload shorter than header token_count";
    munmap(map, st.st_size);
    return false;
  }
  L->cur.map = map;
  L->cur.bytes = st.st_size;
  L->cur.tokens = reinterpret_cast<const uint16_t*>(
      static_cast<const char*>(map) + kHeaderBytes);
  L->cur.count = count;
  return true;
}

// Assemble one batch into out (2*B*T int32: inputs then targets).
// Returns 1 on success, 0 on end-of-data, -1 on error (err set).
int produce(Loader* L, int32_t* out, std::string* err) {
  const int64_t local = L->batch * L->seq;
  const int64_t global = local * L->world;
  while (L->cur.tokens == nullptr || L->pos + global >= L->cur.count) {
    if (L->shard_idx >= L->paths.size()) return 0;
    if (!open_shard(L, L->paths[L->shard_idx++], err)) return -1;
    L->pos = 0;
  }
  const uint16_t* base = L->cur.tokens + L->pos + int64_t(L->rank) * local;
  int32_t* inp = out;
  int32_t* tgt = out + local;
  for (int64_t i = 0; i < local; ++i) {
    inp[i] = base[i];
    tgt[i] = base[i + 1];
  }
  L->pos += global;
  return 1;
}

void producer_main(Loader* L) {
  const int64_t local = L->batch * L->seq;
  for (;;) {
    std::vector<int32_t> buf(2 * local);
    std::string err;
    int rc = produce(L, buf.data(), &err);
    std::unique_lock<std::mutex> lk(L->mu);
    if (rc <= 0) {
      if (rc < 0) L->error = err;
      L->exhausted = true;
      L->cv_can_pop.notify_all();
      return;
    }
    L->cv_can_push.wait(lk, [L] {
      return L->stopping || int(L->ready.size()) < L->depth;
    });
    if (L->stopping) return;
    L->ready.push_back(std::move(buf));
    L->cv_can_pop.notify_one();
  }
}

}  // namespace

extern "C" {

Loader* pdt_loader_create(const char** paths, int n_paths, int64_t batch,
                          int64_t seq, int rank, int world,
                          int prefetch_depth) {
  if (n_paths <= 0 || batch <= 0 || seq <= 0 || world <= 0 || rank < 0 ||
      rank >= world || prefetch_depth <= 0) {
    return nullptr;
  }
  Loader* L = new Loader();
  L->paths.assign(paths, paths + n_paths);
  L->batch = batch;
  L->seq = seq;
  L->rank = rank;
  L->world = world;
  L->depth = prefetch_depth;
  L->worker = std::thread(producer_main, L);
  return L;
}

// 1 = batch written, 0 = end of data, -1 = error (see pdt_loader_error).
int pdt_loader_next(Loader* L, int32_t* inputs, int32_t* targets) {
  const int64_t local = L->batch * L->seq;
  std::vector<int32_t> buf;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_can_pop.wait(lk, [L] { return !L->ready.empty() || L->exhausted; });
    if (L->ready.empty()) {
      return L->error.empty() ? 0 : -1;
    }
    buf = std::move(L->ready.front());
    L->ready.pop_front();
  }
  L->cv_can_push.notify_one();
  std::memcpy(inputs, buf.data(), local * sizeof(int32_t));
  std::memcpy(targets, buf.data() + local, local * sizeof(int32_t));
  return 1;
}

// Restart the stream from the first shard (fresh __iter__ semantics).
void pdt_loader_reset(Loader* L) {
  L->stop_worker();
  L->cur.close();
  L->shard_idx = 0;
  L->pos = 0;
  L->ready.clear();
  L->exhausted = false;
  L->stopping = false;
  L->error.clear();
  L->worker = std::thread(producer_main, L);
}

const char* pdt_loader_error(Loader* L) {
  std::lock_guard<std::mutex> g(L->mu);
  return L->error.c_str();
}

void pdt_loader_destroy(Loader* L) { delete L; }

}  // extern "C"
