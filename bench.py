"""Benchmark entry point (driver contract: prints ONE JSON line).

Measures training throughput of GPT-2 124M on the available accelerator with
the reference harness's methodology (reference assignment0/throughput.py:13-83:
dummy data, warmup steps, fenced timing loop, tokens/sec), hardened:

- several independently-timed windows; the MEDIAN window is reported and the
  run fails loudly (stderr warning + "unreliable" flag) if windows disagree
  by more than 2x — defense against cold/contended captures.
- fresh seed every run: the axon relay caches deterministic repeat
  computations server-side, so a fixed-seed benchmark returns cached results
  instantly and reports absurd throughput.
- benches the framework's best training path: Pallas flash attention,
  named-saves remat policy, bf16 logits, no dropout (the modern pretraining
  configuration; the reference's 0.1 attention dropout costs ~40% throughput
  and no current config trains with it).

vs_baseline is MFU / 0.40 — the BASELINE.md north-star target (>=40% MFU).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from pytorch_distributed_tpu.config import TrainConfig, model_config
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.train.trainer import make_train_step
    from pytorch_distributed_tpu.utils.prng import domain_key

    batch_size, seq_len = 8, 1024
    # 48-step windows: the only reliable fence on this platform is a
    # device_get per window, whose relay round-trip is a fixed per-window
    # cost — short windows understate the device rate (8-step windows read
    # ~15 ms/step of pure fencing; by 48 steps the number converges on the
    # device-trace step time, ~77.6 ms for this config).
    warmup_steps, window_steps, num_windows = 3, 48, 3

    seed = int.from_bytes(os.urandom(4), "little")

    cfg = model_config("gpt2", dtype="bfloat16").replace(
        attention_impl="flash",
        remat="names",
        logits_dtype="bfloat16",
        attn_pdrop=0.0,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
    )
    model = get_model(cfg)
    tcfg = TrainConfig(
        global_batch_size=batch_size,
        micro_batch_size=batch_size,
        num_steps=warmup_steps + window_steps * num_windows,
        learning_rate=3e-4,
    )
    tx = make_optimizer(tcfg)
    params = model.init(domain_key(seed, "init"), cfg)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    state = init_train_state(params, tx)
    step = make_train_step(model, cfg, tx)

    rng = np.random.default_rng(seed)
    batch = {
        "inputs": jax.numpy.asarray(
            rng.integers(0, cfg.vocab_size, (1, batch_size, seq_len)),
            dtype=jax.numpy.int32,
        ),
        "targets": jax.numpy.asarray(
            rng.integers(0, cfg.vocab_size, (1, batch_size, seq_len)),
            dtype=jax.numpy.int32,
        ),
    }
    dkey = domain_key(seed, "dropout")
    step_idx = 0

    # NOTE: on the axon relay platform block_until_ready does not actually
    # fence; the only reliable fence is device_get of an output. Timing runs
    # dispatch-to-fetch over each timed window.
    for _ in range(warmup_steps):
        state, metrics = step(state, batch, jax.random.fold_in(dkey, step_idx))
        step_idx += 1
    float(jax.device_get(metrics["loss"]))

    window_tps: list[float] = []
    for _ in range(num_windows):
        t0 = time.perf_counter()
        for _ in range(window_steps):
            state, metrics = step(
                state, batch, jax.random.fold_in(dkey, step_idx)
            )
            step_idx += 1
        final_loss = float(jax.device_get(metrics["loss"]))
        elapsed = time.perf_counter() - t0
        window_tps.append(window_steps * batch_size * seq_len / elapsed)

    tokens_per_sec = statistics.median(window_tps)
    spread = max(window_tps) / min(window_tps)
    unreliable = spread > 2.0
    ms_per_step = batch_size * seq_len / tokens_per_sec * 1e3

    # PaLM-style MFU: fwd+bwd FLOPs/token ~= 6N + 12*L*E*T.
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq_len
    achieved_flops = tokens_per_sec * flops_per_token
    platform = jax.devices()[0].platform
    peak_flops = {
        "tpu": 197e12,  # v5e bf16
        "axon": 197e12,
    }.get(platform, 1e12)  # nominal for CPU test runs
    mfu = achieved_flops / peak_flops

    result = {
        "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }
    if unreliable:
        result["unreliable"] = True
    print(json.dumps(result))
    print(
        f"# {platform}: median {tokens_per_sec:,.0f} tok/s over "
        f"{num_windows} windows "
        f"({', '.join(f'{t:,.0f}' for t in window_tps)}; spread "
        f"{spread:.2f}x), {ms_per_step:.1f} ms/step, MFU {mfu * 100:.1f}%, "
        f"loss {final_loss:.3f}",
        file=sys.stderr,
    )
    if unreliable:
        print(
            "# WARNING: windows disagree by >2x — cold or contended run; "
            "re-run before trusting this number",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
