"""Benchmark entry point (driver contract: prints ONE JSON line).

Measures training throughput of GPT-2 124M on the available accelerator with
the reference harness's methodology (reference assignment0/throughput.py:13-83:
dummy data, warmup steps, fenced timing loop, tokens/sec), plus MFU.

vs_baseline is MFU / 0.40 — the BASELINE.md north-star target (≥40% MFU).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from pytorch_distributed_tpu.config import TrainConfig, model_config
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.train.trainer import make_train_step
    from pytorch_distributed_tpu.utils.prng import domain_key

    batch_size, seq_len = 8, 1024
    warmup_steps, timed_steps = 3, 10

    # Fresh seed every run: the axon relay caches deterministic repeat
    # computations server-side, so a fixed-seed benchmark returns cached
    # results instantly and reports absurd throughput.
    seed = int.from_bytes(os.urandom(4), "little")

    cfg = model_config("gpt2", remat="dots", dtype="bfloat16")
    model = get_model(cfg)
    tcfg = TrainConfig(
        global_batch_size=batch_size,
        micro_batch_size=batch_size,
        num_steps=warmup_steps + timed_steps,
        learning_rate=3e-4,
    )
    tx = make_optimizer(tcfg)
    params = model.init(domain_key(seed, "init"), cfg)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    state = init_train_state(params, tx)
    step = make_train_step(model, cfg, tx)

    rng = np.random.default_rng(seed)
    batch = {
        "inputs": jax.numpy.asarray(
            rng.integers(0, cfg.vocab_size, (1, batch_size, seq_len)),
            dtype=jax.numpy.int32,
        ),
        "targets": jax.numpy.asarray(
            rng.integers(0, cfg.vocab_size, (1, batch_size, seq_len)),
            dtype=jax.numpy.int32,
        ),
    }
    dkey = domain_key(seed, "dropout")

    # NOTE: on the axon relay platform block_until_ready does not actually
    # fence; the only reliable fence is device_get of an output. Timing runs
    # dispatch-to-fetch over the whole timed window.
    for i in range(warmup_steps):
        state, metrics = step(state, batch, jax.random.fold_in(dkey, i))
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    for i in range(timed_steps):
        state, metrics = step(
            state, batch, jax.random.fold_in(dkey, warmup_steps + i)
        )
    final_loss = float(jax.device_get(metrics["loss"]))
    elapsed = time.perf_counter() - t0

    tokens = timed_steps * batch_size * seq_len
    tokens_per_sec = tokens / elapsed

    # PaLM-style MFU: fwd+bwd FLOPs/token ~= 6N + 12*L*E*T.
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq_len
    achieved_flops = tokens_per_sec * flops_per_token
    platform = jax.devices()[0].platform
    peak_flops = {
        "tpu": 197e12,  # v5e bf16
        "axon": 197e12,
    }.get(platform, 1e12)  # nominal for CPU test runs
    mfu = achieved_flops / peak_flops

    print(
        json.dumps(
            {
                "metric": "gpt2_124m_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.40, 4),
            }
        )
    )
    print(
        f"# {platform}: {tokens_per_sec:,.0f} tok/s, "
        f"MFU {mfu * 100:.1f}%, loss {final_loss:.3f}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
