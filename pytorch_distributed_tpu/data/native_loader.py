"""ctypes binding for the native (C++) prefetching shard loader.

``NativeTokenShardLoader`` is a drop-in for
``DistributedTokenShardLoader`` (same lockstep rank-sliced stream, reference
distributed_data_loader.py:16-24) backed by ``native/data_loader.cc``:
mmap'd shards, batch assembly in C++, and a background producer thread that
keeps ``prefetch_depth`` ready batches ahead of the host loop — IO and
int32 upcasting overlap with accelerator compute instead of serialising
against it.

The shared library is built on demand with ``make`` (g++; no pybind11 —
plain C ABI through ctypes). If no C++ toolchain is available, import still
succeeds and construction raises with a pointer to the pure-numpy loaders.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Iterator

import numpy as np

from pytorch_distributed_tpu.data import bin_format

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libpdtpu_data.so"
_lib: ctypes.CDLL | None = None


class NativeLoaderUnavailable(RuntimeError):
    pass


def _build_library() -> None:
    src = _NATIVE_DIR / "data_loader.cc"
    if not src.exists():
        raise NativeLoaderUnavailable(f"native source missing: {src}")
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            text=True,
            timeout=300,
        )
    except FileNotFoundError as e:
        raise NativeLoaderUnavailable(
            "`make` not available; use the numpy loaders "
            "(data.loader / data.distributed_loader) instead"
        ) from e
    except subprocess.CalledProcessError as e:
        raise NativeLoaderUnavailable(
            f"native loader build failed:\n{e.stderr}"
        ) from e


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    src = _NATIVE_DIR / "data_loader.cc"
    if not _LIB_PATH.exists() or (
        src.exists() and src.stat().st_mtime > _LIB_PATH.stat().st_mtime
    ):
        _build_library()
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.pdt_loader_create.restype = ctypes.c_void_p
    lib.pdt_loader_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
        ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.pdt_loader_next.restype = ctypes.c_int
    lib.pdt_loader_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.pdt_loader_reset.restype = None
    lib.pdt_loader_reset.argtypes = [ctypes.c_void_p]
    lib.pdt_loader_error.restype = ctypes.c_char_p
    lib.pdt_loader_error.argtypes = [ctypes.c_void_p]
    lib.pdt_loader_destroy.restype = None
    lib.pdt_loader_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeTokenShardLoader:
    """Rank-sliced lockstep shard loader, C++-backed, prefetching.

    Same stream as ``DistributedTokenShardLoader`` (world=1 ==> the plain
    sequential stream in its lockstep form). Yields host int32
    (inputs, targets) [B, T] batches.
    """

    def __init__(
        self,
        file_paths,
        local_batch_size: int,
        sequence_length: int,
        *,
        rank: int = 0,
        world_size: int = 1,
        prefetch_depth: int = 2,
    ):
        self.files = sorted(str(f) for f in file_paths)
        if not self.files:
            raise ValueError("empty shard file list")
        if not (0 <= rank < world_size):
            raise ValueError(
                f"rank {rank} out of range for world_size {world_size}"
            )
        # Validate headers up front in Python so malformed shards raise the
        # same ShardFormatError as the numpy path (the C++ side re-checks).
        for f in self.files:
            bin_format.read_header(f)
        self.local_batch_size = int(local_batch_size)
        self.sequence_length = int(sequence_length)
        self.rank, self.world_size = rank, world_size
        self._lib = _load_library()
        arr = (ctypes.c_char_p * len(self.files))(
            *[f.encode() for f in self.files]
        )
        self._handle = self._lib.pdt_loader_create(
            arr, len(self.files),
            self.local_batch_size, self.sequence_length,
            rank, world_size, prefetch_depth,
        )
        if not self._handle:
            raise NativeLoaderUnavailable("pdt_loader_create failed")

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        self._lib.pdt_loader_reset(self._handle)
        b, t = self.local_batch_size, self.sequence_length
        while True:
            inputs = np.empty((b, t), dtype=np.int32)
            targets = np.empty((b, t), dtype=np.int32)
            rc = self._lib.pdt_loader_next(
                self._handle,
                inputs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                targets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            if rc == 0:
                return
            if rc < 0:
                msg = self._lib.pdt_loader_error(self._handle) or b""
                raise bin_format.ShardFormatError(msg.decode())
            yield inputs, targets

    def get_total_tokens(self) -> int:
        return bin_format.total_tokens(self.files)

    def get_info(self) -> dict:
        return {
            "num_shards": len(self.files),
            "batch_size": self.local_batch_size,
            "sequence_length": self.sequence_length,
            "rank": self.rank,
            "world_size": self.world_size,
            "files": self.files,
            "total_tokens": self.get_total_tokens(),
            "backend": "native (C++ mmap + prefetch)",
        }

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.pdt_loader_destroy(handle)
            self._handle = None
