from pytorch_distributed_tpu.data.bin_format import (  # noqa: F401
    HEADER_INTS,
    MAGIC,
    VERSION,
    read_header,
    read_tokens,
    write_shard,
)
from pytorch_distributed_tpu.data.loader import TokenShardLoader  # noqa: F401
from pytorch_distributed_tpu.data.distributed_loader import (  # noqa: F401
    DistributedTokenShardLoader,
)
from pytorch_distributed_tpu.data.synthetic import make_synthetic_shards  # noqa: F401
from pytorch_distributed_tpu.data.text import (  # noqa: F401
    BYTE_VOCAB_SIZE,
    decode_bytes,
    encode_bytes,
    tokenize_files,
)
