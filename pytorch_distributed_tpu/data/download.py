"""fineweb10B pretokenized shard downloader.

Capability twin of reference data/data_loader.py:9-65
(``download_fineweb10B_files``): pulls the ``kjj0/fineweb10B-gpt2`` dataset's
pretokenized shards from the HF Hub into a local cache dir — 1 validation file
plus up to 103 train files ``fineweb_train_%06d.bin`` — skipping files that
already exist.

Network access is optional at import time; in zero-egress environments use
``pytorch_distributed_tpu.data.synthetic`` instead.
"""

from __future__ import annotations

import os
from pathlib import Path

REPO_ID = "kjj0/fineweb10B-gpt2"
VAL_FILE = "fineweb_val_%06d.bin"
TRAIN_FILE = "fineweb_train_%06d.bin"
MAX_TRAIN_FILES = 103


def download_fineweb10B_files(
    data_dir: str | Path = ".cache/data/fineweb10B",
    num_train_files: int = 10,
) -> list[str]:
    """Download val shard + first ``num_train_files`` train shards.

    Returns local train-file paths (sorted). Skips already-present files
    (reference :28-41,44-62 behavior).
    """
    try:
        from huggingface_hub import hf_hub_download
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "huggingface_hub is unavailable; generate local data with "
            "pytorch_distributed_tpu.data.synthetic.make_synthetic_shards"
        ) from e

    num_train_files = min(num_train_files, MAX_TRAIN_FILES)
    data_dir = Path(data_dir)
    os.makedirs(data_dir, exist_ok=True)

    def fetch(name: str) -> str:
        local = data_dir / name
        if local.exists():
            return str(local)
        got = hf_hub_download(
            repo_id=REPO_ID,
            filename=name,
            repo_type="dataset",
            local_dir=str(data_dir),
        )
        return str(got)

    fetch(VAL_FILE % 0)
    train_paths = [
        fetch(TRAIN_FILE % (i + 1)) for i in range(num_train_files)
    ]
    return sorted(train_paths)
