"""Deterministic rank-sliced distributed loader.

Behavioral twin of the reference ``DistributedKJJ0DataLoader``
(reference data/distributed_data_loader.py:9-110, worked example :16-24),
with the TODO-hinted math completed:

- all processes read the same files in the same order;
- per batch, process r takes the contiguous chunk
  ``tokens[pos + r*B*T : pos + (r+1)*B*T + 1]`` (+1 for the target shift)
  and reshapes it to [B, T];
- every process then advances ``pos += world*B*T``;
- shard switch when fewer than ``world*B*T + 1`` tokens remain
  (so all processes switch in lockstep — deterministic and equivalent to the
  single-process stream).

TPU-native identity: rank/world default to ``jax.process_index()`` /
``jax.process_count()`` — the mesh-runtime replacement for torchrun's
RANK/WORLD_SIZE env vars (reference :44-48) — but can be passed explicitly
(e.g. one logical slice per mesh data-axis coordinate).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from pytorch_distributed_tpu.data.loader import TokenShardLoader


class DistributedTokenShardLoader(TokenShardLoader):
    def __init__(
        self,
        file_paths,
        local_batch_size: int,
        sequence_length: int,
        *,
        rank: int | None = None,
        world_size: int | None = None,
        mmap: bool = True,
    ):
        if rank is None or world_size is None:
            import jax

            rank = jax.process_index() if rank is None else rank
            world_size = jax.process_count() if world_size is None else world_size
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world_size {world_size}")
        self.rank = rank
        self.world_size = world_size
        self.local_batch_size = local_batch_size
        super().__init__(
            file_paths, local_batch_size, sequence_length, mmap=mmap
        )

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        self._begin_iteration()
        b, t = self.local_batch_size, self.sequence_length
        num_tokens_local = b * t  # reference TODO 2 (:69-70)
        num_tokens_global = self.world_size * num_tokens_local

        while True:
            # Lockstep shard switch: need the whole global chunk + 1 to fit
            # (reference :79-85 condition uses world*B*T), so every process
            # always finds its full slice — including the last rank's +1
            # target lookahead — in the current shard.
            if not self._advance_shard_if_needed(num_tokens_global):
                return

            # reference TODO 3 (:83-87): this rank's slice start.
            pos_local = self.current_position + self.rank * num_tokens_local
            buf = np.asarray(
                self.current_tokens[pos_local : pos_local + num_tokens_local + 1],
                dtype=np.int32,
            )
            inputs = buf[:-1].reshape(b, t)
            targets = buf[1:].reshape(b, t)

            # reference TODO 4 (:100-103): all ranks advance together.
            self.current_position += num_tokens_global

            yield inputs, targets
