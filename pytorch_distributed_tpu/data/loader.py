"""Sequential token-shard loader.

Behavioral twin of the reference ``KJJ0DataLoader``
(reference data/data_loader.py:68-220): reads sorted shard files in order,
yields [B, T] (inputs, targets) batches where each of the B sequences pulls
T+1 tokens (targets are inputs shifted by one) and the read position advances
by T per sequence; switches shards when fewer than T+1 tokens remain; a fresh
``__iter__`` restarts from the first shard.

TPU-first differences:
- shards are memory-mapped (OS page cache), not bulk-read;
- batches are yielded as host numpy int32 arrays; device placement/sharding
  is the trainer's job (``jax.device_put`` with the batch sharding), keeping
  the loader process- and device-topology-agnostic;
- batch assembly is one vectorised strided gather, not a Python stack loop.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

import numpy as np

from pytorch_distributed_tpu.data import bin_format


class TokenShardLoader:
    def __init__(
        self,
        file_paths,
        batch_size: int,
        sequence_length: int,
        *,
        mmap: bool = True,
    ):
        self.files = sorted(str(f) for f in file_paths)
        if not self.files:
            raise ValueError("empty shard file list")
        self.batch_size = batch_size
        self.sequence_length = sequence_length
        self._mmap = mmap
        self._reset()

    # -- state ------------------------------------------------------------
    def _reset(self) -> None:
        self.current_shard_idx = 0
        self.current_tokens: np.ndarray | None = None
        self.current_position = 0

    def _advance_shard_if_needed(self, needed_tokens: int | None = None) -> bool:
        """Ensure > ``needed_tokens`` tokens remain past the current position;
        returns False when data is exhausted.

        Mirrors the reference's shard-switch condition
        (data_loader.py:147: pos + T >= len(tokens)); the distributed loader
        passes world*B*T so all processes switch shards in lockstep."""
        t = needed_tokens if needed_tokens is not None else self.sequence_length
        while (
            self.current_tokens is None
            or self.current_position + t >= len(self.current_tokens)
        ):
            if self.current_shard_idx >= len(self.files):
                return False
            self.current_tokens = bin_format.read_tokens(
                self.files[self.current_shard_idx], mmap=self._mmap
            )
            self.current_shard_idx += 1
            self.current_position = 0
        return True

    # -- resumable position (beyond reference: its loader restarts from
    # shard 0 on every run, so checkpoint-resumed training repeats data) --
    def state_dict(self) -> dict:
        """Stream position for checkpointing: next-shard index + offset."""
        return {
            "shard_idx": int(self.current_shard_idx),
            "position": int(self.current_position),
        }

    def load_state_dict(self, sd: dict) -> None:
        """Restore a position captured by ``state_dict``; takes effect at
        the next ``__iter__`` (instead of rewinding to shard 0)."""
        self._pending_state = (int(sd["shard_idx"]), int(sd["position"]))

    def _begin_iteration(self) -> None:
        pending = getattr(self, "_pending_state", None)
        self._reset()
        if pending is not None:
            self._pending_state = None
            idx, pos = pending
            if idx > 0:
                if idx > len(self.files):
                    raise ValueError(
                        f"loader state shard_idx {idx} exceeds "
                        f"{len(self.files)} shards"
                    )
                self.current_tokens = bin_format.read_tokens(
                    self.files[idx - 1], mmap=self._mmap
                )
                self.current_shard_idx = idx
                self.current_position = pos

    # -- iteration --------------------------------------------------------
    def _next_batch(self) -> tuple[np.ndarray, np.ndarray] | None:
        b, t = self.batch_size, self.sequence_length
        inputs = np.empty((b, t), dtype=np.int32)
        targets = np.empty((b, t), dtype=np.int32)
        for i in range(b):
            if not self._advance_shard_if_needed():
                return None
            pos = self.current_position
            seq = np.asarray(self.current_tokens[pos : pos + t + 1], dtype=np.int32)
            inputs[i] = seq[:-1]
            targets[i] = seq[1:]
            self.current_position += t
        return inputs, targets

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        self._begin_iteration()
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            yield batch

    # -- metadata ---------------------------------------------------------
    def get_total_tokens(self) -> int:
        return bin_format.total_tokens(self.files)

    def get_info(self) -> dict:
        return {
            "num_shards": len(self.files),
            "batch_size": self.batch_size,
            "sequence_length": self.sequence_length,
            "files": self.files,
            "total_tokens": self.get_total_tokens(),
        }
