"""The kjj0 pretokenized ``.bin`` shard format.

Layout (reference data/data_loader.py:104-135):
  - header: 256 int32 little-endian values (1024 bytes)
      header[0] = 20240520 (magic), header[1] = 1 (version),
      header[2] = token_count
  - payload: token_count uint16 tokens

This module is pure numpy (read + write — the writer also backs synthetic
test/bench data, which the reference lacks). Tokens stay uint16 on the host;
callers upcast to int32 at batch-assembly time to avoid doubling host RAM.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

MAGIC = 20240520
VERSION = 1
HEADER_INTS = 256
HEADER_BYTES = HEADER_INTS * 4


class ShardFormatError(ValueError):
    pass


def read_header(path: str | Path) -> dict:
    """Read and validate the 1 KiB header; returns magic/version/token_count."""
    with open(path, "rb") as f:
        raw = f.read(HEADER_BYTES)
    if len(raw) < HEADER_BYTES:
        raise ShardFormatError(f"{path}: truncated header ({len(raw)} bytes)")
    header = np.frombuffer(raw, dtype="<i4")
    if header[0] != MAGIC:
        raise ShardFormatError(
            f"{path}: bad magic {int(header[0])}, expected {MAGIC}"
        )
    if header[1] != VERSION:
        raise ShardFormatError(
            f"{path}: unsupported version {int(header[1])}, expected {VERSION}"
        )
    return {
        "magic": int(header[0]),
        "version": int(header[1]),
        "token_count": int(header[2]),
    }


def read_tokens(path: str | Path, *, mmap: bool = True) -> np.ndarray:
    """Return the uint16 token array of a shard.

    mmap=True maps the payload (zero-copy, lets the OS page cache manage host
    RAM — preferable to the reference's bulk ``f.read`` of the whole shard).
    """
    info = read_header(path)
    count = info["token_count"]
    if mmap:
        tokens = np.memmap(
            path, dtype="<u2", mode="r", offset=HEADER_BYTES, shape=(count,)
        )
    else:
        with open(path, "rb") as f:
            f.seek(HEADER_BYTES)
            tokens = np.frombuffer(f.read(count * 2), dtype="<u2")
    if len(tokens) != count:
        raise ShardFormatError(
            f"{path}: token count mismatch: got {len(tokens)}, expected {count}"
        )
    return tokens


def write_shard(path: str | Path, tokens: np.ndarray) -> None:
    """Write a uint16 token array as a kjj0-format shard."""
    tokens = np.asarray(tokens)
    if tokens.dtype != np.uint16:
        if tokens.min() < 0 or tokens.max() >= 2**16:
            raise ShardFormatError("tokens out of uint16 range")
        tokens = tokens.astype(np.uint16)
    header = np.zeros(HEADER_INTS, dtype="<i4")
    header[0] = MAGIC
    header[1] = VERSION
    header[2] = len(tokens)
    path = Path(path)
    os.makedirs(path.parent, exist_ok=True)
    with open(path, "wb") as f:
        f.write(header.tobytes())
        f.write(tokens.astype("<u2").tobytes())


def total_tokens(paths) -> int:
    """Sum token counts across shards, reading headers only
    (reference data_loader.py:197-207)."""
    return sum(read_header(p)["token_count"] for p in paths)
