"""Raw-text -> pretokenized `.bin` shards (byte-level, zero dependencies).

The reference's data story starts from DOWNLOADED pretokenized fineweb10B
shards (reference data/data_loader.py:9-65); users with their own corpora
have no path in. This module closes that gap without any network or
tokenizer assets: text is encoded byte-level (UTF-8 bytes ARE the tokens,
vocab 256 + one document separator), written in the same kjj0 `.bin`
format (data/bin_format.py), so every loader — sequential, distributed,
native C++ — consumes it unmodified. Train with
``ModelConfig(vocab_size=257)``.

For subword tokenization, pass any callable ``encode(text) -> list[int]``
(e.g. a HuggingFace tokenizer's) to ``tokenize_files``; byte-level is only
the dependency-free default.

CLI: ``python scripts/tokenize_text.py corpus/*.txt -o .cache/data/mine``.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Iterable, Sequence

import numpy as np

from pytorch_distributed_tpu.data import bin_format

# Byte-level vocabulary: 0..255 raw bytes, 256 document separator.
BYTE_VOCAB_SIZE = 257
DOC_SEPARATOR = 256


def encode_bytes(text: str) -> list[int]:
    """UTF-8 byte-level encoding — every string round-trips losslessly."""
    return list(text.encode("utf-8"))


def decode_bytes(tokens: Iterable[int]) -> str:
    """Inverse of encode_bytes; separator tokens become newlines."""
    out = bytearray()
    for t in tokens:
        if t == DOC_SEPARATOR:
            out += b"\n"
        elif 0 <= t < 256:
            out.append(t)
    return out.decode("utf-8", errors="replace")


def _check_uint16(arr: np.ndarray) -> np.ndarray:
    """Vectorised range check: np.uint16 conversion would WRAP silently
    (a per-token Python loop here is interpreter-bound on real corpora)."""
    if arr.size and (int(arr.min()) < 0 or int(arr.max()) >= 2**16):
        bad = int(arr[(arr < 0) | (arr >= 2**16)][0])
        raise ValueError(
            f"token {bad} out of uint16 range (the .bin format "
            "stores uint16; vocab must be < 65536)"
        )
    return arr.astype(np.uint16)


class _ShardWriter:
    """Accumulates uint16 token arrays and emits fixed-size `.bin` shards.

    Memory is bounded at ~2 bytes x (shard_tokens + one appended array):
    tokens live in numpy uint16 chunks, never Python int lists (which cost
    ~28 B/token transient and OOM the host on multi-GB corpora)."""

    def __init__(self, out_dir: Path, prefix: str, shard_tokens: int):
        self.out_dir = out_dir
        self.prefix = prefix
        self.shard_tokens = shard_tokens
        self.parts: list[np.ndarray] = []
        self.total = 0
        self.shards: list[Path] = []

    def append(self, arr: np.ndarray) -> None:
        if not arr.size:
            return
        self.parts.append(arr)
        self.total += arr.size
        if self.total < self.shard_tokens:
            return
        # ONE concatenation per append, then emit every full shard from it
        # in a single pass — re-merging the remainder per shard would copy
        # O(N^2 / shard_tokens) bytes on huge appends.
        merged = np.concatenate(self.parts)
        n_full = merged.size // self.shard_tokens
        for i in range(n_full):
            self._write(
                merged[i * self.shard_tokens : (i + 1) * self.shard_tokens]
            )
        rest = merged[n_full * self.shard_tokens :]
        self.parts = [rest] if rest.size else []
        self.total = int(rest.size)

    def finish(self) -> list[Path]:
        if self.total:
            self._write(np.concatenate(self.parts))
            self.parts, self.total = [], 0
        return self.shards

    def _write(self, tokens: np.ndarray) -> None:
        path = self.out_dir / f"{self.prefix}_{len(self.shards):06d}.bin"
        bin_format.write_shard(path, tokens)
        self.shards.append(path)


def tokenize_files(
    paths: Sequence[str | Path],
    out_dir: str | Path,
    *,
    shard_tokens: int = 10_000_000,
    encode: Callable[[str], list[int]] = encode_bytes,
    separator: int | None = DOC_SEPARATOR,
    prefix: str = "text_train",
    chunk_bytes: int = 1 << 22,
) -> list[Path]:
    """Tokenize text files into fixed-size `.bin` shards in bounded memory.

    Each input file is one document; ``separator`` (if not None) is
    appended after each so the model sees document boundaries. Returns the
    shard paths (``{prefix}_{idx:06d}.bin``), ready for TokenShardLoader.

    Memory: with the byte-level default encoder, files stream through in
    ~``chunk_bytes``-character TEXT-mode chunks (the incremental UTF-8
    decoder handles multi-byte characters split across chunks; text mode
    keeps the exact semantics of the whole-file path — universal-newline
    translation and a hard UnicodeDecodeError on invalid UTF-8) and peak
    host memory is bounded by ~2 x shard_tokens + chunk bytes regardless
    of corpus size. A custom ``encode`` (e.g. a HF tokenizer) must see
    each whole document — BPE merges can span any chunk boundary — so
    those files are read fully, but tokens still buffer as numpy uint16
    (~2 B/token instead of a Python list's ~28 B/token transient).
    """
    if not paths:
        raise ValueError("tokenize_files needs at least one input path")
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    writer = _ShardWriter(out_dir, prefix, shard_tokens)
    sep_arr = (
        _check_uint16(np.asarray([separator], dtype=np.int64))
        if separator is not None
        else None
    )

    for p in paths:
        if encode is encode_bytes:
            # Streaming path: byte-level tokens depend only on the local
            # character, so chunk boundaries cannot change the encoding.
            with open(p, "r", encoding="utf-8") as f:
                while True:
                    chunk = f.read(chunk_bytes)
                    if not chunk:
                        break
                    writer.append(
                        np.frombuffer(
                            chunk.encode("utf-8"), dtype=np.uint8
                        ).astype(np.uint16)
                    )
        else:
            toks = encode(Path(p).read_text(encoding="utf-8"))
            writer.append(
                _check_uint16(np.asarray(toks, dtype=np.int64))
            )
        if sep_arr is not None:
            writer.append(sep_arr)
    return writer.finish()
