"""Raw-text -> pretokenized `.bin` shards (byte-level, zero dependencies).

The reference's data story starts from DOWNLOADED pretokenized fineweb10B
shards (reference data/data_loader.py:9-65); users with their own corpora
have no path in. This module closes that gap without any network or
tokenizer assets: text is encoded byte-level (UTF-8 bytes ARE the tokens,
vocab 256 + one document separator), written in the same kjj0 `.bin`
format (data/bin_format.py), so every loader — sequential, distributed,
native C++ — consumes it unmodified. Train with
``ModelConfig(vocab_size=257)``.

For subword tokenization, pass any callable ``encode(text) -> list[int]``
(e.g. a HuggingFace tokenizer's) to ``tokenize_files``; byte-level is only
the dependency-free default.

CLI: ``python scripts/tokenize_text.py corpus/*.txt -o .cache/data/mine``.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Iterable, Sequence

import numpy as np

from pytorch_distributed_tpu.data import bin_format

# Byte-level vocabulary: 0..255 raw bytes, 256 document separator.
BYTE_VOCAB_SIZE = 257
DOC_SEPARATOR = 256


def encode_bytes(text: str) -> list[int]:
    """UTF-8 byte-level encoding — every string round-trips losslessly."""
    return list(text.encode("utf-8"))


def decode_bytes(tokens: Iterable[int]) -> str:
    """Inverse of encode_bytes; separator tokens become newlines."""
    out = bytearray()
    for t in tokens:
        if t == DOC_SEPARATOR:
            out += b"\n"
        elif 0 <= t < 256:
            out.append(t)
    return out.decode("utf-8", errors="replace")


def tokenize_files(
    paths: Sequence[str | Path],
    out_dir: str | Path,
    *,
    shard_tokens: int = 10_000_000,
    encode: Callable[[str], list[int]] = encode_bytes,
    separator: int | None = DOC_SEPARATOR,
    prefix: str = "text_train",
) -> list[Path]:
    """Tokenize text files into fixed-size `.bin` shards.

    Each input file is one document; ``separator`` (if not None) is
    appended after each so the model sees document boundaries. Returns the
    shard paths (``{prefix}_{idx:06d}.bin``), ready for TokenShardLoader.
    """
    if not paths:
        raise ValueError("tokenize_files needs at least one input path")
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    shards: list[Path] = []
    buf: list[int] = []

    def flush() -> None:
        if not buf:
            return
        path = out_dir / f"{prefix}_{len(shards):06d}.bin"
        bin_format.write_shard(path, np.asarray(buf, dtype=np.uint16))
        shards.append(path)
        buf.clear()

    for p in paths:
        toks = encode(Path(p).read_text(encoding="utf-8"))
        if separator is not None:
            toks = list(toks) + [separator]
        # Vectorised range check: np.uint16 conversion would WRAP silently
        # (a per-token Python loop here is interpreter-bound on real
        # corpora).
        arr = np.asarray(toks, dtype=np.int64)
        if arr.size and (int(arr.min()) < 0 or int(arr.max()) >= 2**16):
            bad = int(arr[(arr < 0) | (arr >= 2**16)][0])
            raise ValueError(
                f"token {bad} out of uint16 range (the .bin format "
                "stores uint16; vocab must be < 65536)"
            )
        buf.extend(arr.tolist())
        while len(buf) >= shard_tokens:
            head, rest = buf[:shard_tokens], buf[shard_tokens:]
            buf[:] = head
            flush()
            buf[:] = rest
    flush()
    return shards
