"""Synthetic pretokenized data in kjj0 shard format.

The reference has no offline data path (its loaders require downloaded
fineweb10B shards). For zero-egress environments, tests, and benchmarks we
generate deterministic shards with a seeded PRNG — same binary format, so the
whole pipeline downstream of download is exercised unmodified.

The token stream is Markov-ish (a mixture of a repeated-ngram process and
uniform noise) rather than pure uniform, so cross-entropy actually decreases
during smoke training runs.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from pytorch_distributed_tpu.data import bin_format


def synthetic_token_stream(
    num_tokens: int, vocab_size: int, seed: int
) -> np.ndarray:
    rng = np.random.default_rng(seed)
    # Learnable structure: each token is (prev*2 + 1) mod V with p=0.7,
    # uniform otherwise.
    noise = rng.integers(0, vocab_size, size=num_tokens, dtype=np.int64)
    use_noise = rng.random(num_tokens) > 0.7
    out = np.empty(num_tokens, dtype=np.int64)
    prev = int(noise[0])
    for i in range(num_tokens):
        if use_noise[i]:
            prev = int(noise[i])
        else:
            prev = (prev * 2 + 1) % vocab_size
        out[i] = prev
    return out.astype(np.uint16)


def make_synthetic_shards(
    data_dir: str | Path,
    *,
    num_shards: int = 2,
    tokens_per_shard: int = 100_000,
    vocab_size: int = 50257,
    seed: int = 42,
) -> list[str]:
    """Write (or reuse) deterministic shards; returns sorted file paths."""
    if vocab_size > 2**16:
        raise ValueError("synthetic kjj0 shards require vocab_size <= 65536")
    data_dir = Path(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    paths = []
    for i in range(num_shards):
        path = data_dir / f"synthetic_train_{i:06d}.bin"
        if not path.exists():
            tokens = synthetic_token_stream(
                tokens_per_shard, vocab_size, seed + i
            )
            bin_format.write_shard(path, tokens)
        paths.append(str(path))
    return sorted(paths)
