"""Multi-head causal self-attention.

Implementations:
- ``naive``: materialises the full [B, H, T, T] score matrix — the behavioral
  twin of the reference's manual attention math (reference my_gpt2.py:60-77:
  matmul / sqrt(head_dim), masked_fill(-inf), softmax, dropout, matmul).
  TPU-first differences: the causal mask is computed on the fly from iotas
  (no precomputed n_ctx×n_ctx tril buffer as in reference my_gpt2.py:29-36 —
  XLA fuses the compare into the softmax), and softmax runs in float32.
- ``flash``: blockwise Pallas kernel (ops/pallas_flash.py) that never
  materialises the score matrix — O(T) memory.
- ``ring``: sequence-parallel blockwise attention over a mesh axis
  (ops/ring_attention.py).
- ``paged`` (decode only): single-query attention against a PAGED KV
  pool addressed through per-row block tables — the serving block-pool
  layout (serving/engine.PagedBatchedDecodeEngine). Not dispatched
  through ``multi_head_attention`` (it is a decode-cache op, not a
  training attention: one query token, keys gathered by page id);
  re-exported here as ``paged_decode_attention`` so the attention
  surface stays one module. Pallas kernel + XLA gather fallback live in
  ops/paged_kernel.py.

All variants support grouped-query attention (n_kv_head < n_head) for the
llama family.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite mask value: -inf breaks softmax when a row is all-masked


def paged_decode_attention(*args, **kwargs):
    """Lazy re-export of ops/paged_kernel.paged_decode_attention (see
    module docstring): paged single-query decode attention, [B, H, D]
    queries against a [P, page, Hkv, D] pool via [B, n_pages] block
    tables. Lazy so importing the training attention surface never pays
    the Pallas import."""
    from pytorch_distributed_tpu.ops.paged_kernel import (
        paged_decode_attention as impl,
    )

    return impl(*args, **kwargs)


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, T, Hkv, D] -> [B, T, Hkv*n_rep, D] for GQA."""
    if n_rep == 1:
        return x
    b, t, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, t, h, n_rep, d)).reshape(
        b, t, h * n_rep, d
    )


def naive_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    dropout_rate: float = 0.0,
    dropout_key: jax.Array | None = None,
    deterministic: bool = True,
) -> jax.Array:
    """Returns [B, T, H, D]. Scores/softmax computed in float32."""
    b, t, h, d = q.shape
    s = k.shape[1]
    k = _repeat_kv(k, h // k.shape[2])
    v = _repeat_kv(v, h // v.shape[2])

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    # [B, H, T, S] in f32 — one big MXU-friendly batched matmul.
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32
    ) * scale

    if causal:
        # query position i attends to key positions j <= i (+ offset when S>T,
        # i.e. decoding with a cache: the last query aligns with the last key).
        qpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 0) + (s - t)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
        scores = jnp.where(kpos <= qpos, scores, NEG_INF)

    weights = jax.nn.softmax(scores, axis=-1)

    if not deterministic and dropout_rate > 0.0:
        if dropout_key is None:
            raise ValueError("attention dropout requires a PRNG key")
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(dropout_key, p=keep, shape=weights.shape)
        weights = jnp.where(mask, weights / keep, jnp.zeros_like(weights))

    weights = weights.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", weights, v)


def multi_head_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "naive",
    causal: bool = True,
    dropout_rate: float = 0.0,
    dropout_key: jax.Array | None = None,
    deterministic: bool = True,
    seq_axis: str | None = None,
    seq_impl: str = "ring",
) -> jax.Array:
    """Dispatch over attention implementations. Inputs [B, T, H(kv), D].

    ``seq_axis``: name of a shard_map mesh axis the sequence dim is sharded
    over — selects sequence/context parallelism regardless of ``impl``;
    ``seq_impl`` picks the technique: "ring" (KV blocks stream around a
    ppermute ring, online-softmax merge) or "ulysses" (head/sequence
    all-to-all re-shard, full local attention — needs the axis to divide
    the head counts). Attention dropout works under "ulysses" (the local
    attention IS the full-sequence computation on this shard's head group
    — see ops/ulysses.py for the per-shard-key contract) but not "ring",
    where weights only ever exist per KV block inside the online-softmax
    merge. (The reference has no sequence parallelism at all, SURVEY.md
    §5.7.)
    """
    if seq_axis is not None:
        if seq_impl == "ulysses":
            from pytorch_distributed_tpu.ops.ulysses import ulysses_attention

            # Local backend defaults to flash: after the head/sequence
            # re-shard the local attention sees the FULL sequence, and
            # naive's [T_global, T_global] score matrix is exactly what
            # sequence parallelism exists to avoid. "naive" is promoted to
            # flash (same math up to online-softmax reordering); an
            # explicit impl="flash" passes through unchanged.
            # (No promotion note when attention dropout is active — the
            # local backend falls back to naive there anyway, see
            # ops/ulysses.py.)
            if impl == "naive" and (deterministic or dropout_rate == 0.0):
                import warnings

                warnings.warn(
                    "impl='naive' with seq_impl='ulysses' is promoted to "
                    "flash (same math up to online-softmax reordering); "
                    "pass impl='flash' to silence this",
                    stacklevel=2,
                )
            return ulysses_attention(
                q, k, v, axis_name=seq_axis, causal=causal,
                impl="flash" if impl == "naive" else impl,
                dropout_rate=dropout_rate,
                dropout_key=dropout_key,
                deterministic=deterministic,
            )
        if seq_impl != "ring":
            raise KeyError(
                f"unknown seq_impl {seq_impl!r}; known: ring, ulysses"
            )
        if not deterministic and dropout_rate > 0.0:
            raise NotImplementedError(
                "attention dropout is not supported with ring attention "
                "(weights exist only per KV block inside the online-softmax "
                "merge); use seq_impl='ulysses' or attn_pdrop=0.0"
            )
        from pytorch_distributed_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, axis_name=seq_axis, causal=causal)
    if impl == "naive":
        return naive_attention(
            q, k, v,
            causal=causal,
            dropout_rate=dropout_rate,
            dropout_key=dropout_key,
            deterministic=deterministic,
        )
    if impl == "flash":
        from pytorch_distributed_tpu.ops.pallas_flash import flash_attention

        # Flash path has no attention-dropout support (like torch SDPA flash);
        # callers fall back to naive when attn_pdrop>0 and training.
        if not deterministic and dropout_rate > 0.0:
            return naive_attention(
                q, k, v,
                causal=causal,
                dropout_rate=dropout_rate,
                dropout_key=dropout_key,
                deterministic=deterministic,
            )
        return flash_attention(q, k, v, causal=causal)
    raise KeyError(f"unknown attention impl {impl!r}")
