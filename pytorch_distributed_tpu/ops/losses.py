"""Loss functions.

The reference computes flat next-token cross-entropy over all positions
(reference train/trainer.py:53-56: F.cross_entropy on [B*T, V] logits vs
[B*T] targets). Same semantics here, in float32, via log-softmax gather —
no [B*T, V] one-hot materialisation.

``linear_cross_entropy`` additionally fuses the LM-head matmul into the
loss: logits are produced and consumed in vocab blocks inside a scan, so
the full [B·T, V] logits tensor never exists — neither in forward (online
logsumexp) nor in backward (per-block softmax-minus-onehot feeding the
dx/dW matmuls directly). This removes the largest activation in the
training step (823 MB bf16 at GPT-2 bench shapes; 2.1 GB for llama-3
vocabularies) at the cost of recomputing the block logits once in
backward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _vary_like(z, *refs):
    """pcast ``z`` to vary on the union of the refs' varying manual axes —
    shard_map check_vma requires the fused-CE scans' fresh zero carries to
    match the varying outputs their bodies produce (explicit/pipeline
    paths call this op inside shard_map)."""
    from pytorch_distributed_tpu.ops.tp import pvary_missing

    axes: set = set()
    for r in refs:
        axes |= set(getattr(getattr(r, "aval", None), "vma", frozenset()))
    return pvary_missing(z, tuple(axes))


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean token cross-entropy. logits [..., V] float; targets [...] int."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    ).squeeze(-1)
    return jnp.mean(logz - gold)


# --------------------------------------------------------------------------
# fused LM-head + cross-entropy
# --------------------------------------------------------------------------


def _block_logits(x, wblk, ib, block_v, v, dtype, w_layout):
    """One vocab block of logits [N, bv], padding columns masked to -inf."""
    if w_layout == "ve":  # wblk [bv, E]
        dims = (((1,), (1,)), ((), ()))
    else:  # "ev": wblk [E, bv]
        dims = (((1,), (0,)), ((), ()))
    logits = jax.lax.dot_general(
        x, wblk, dims, preferred_element_type=jnp.float32
    ).astype(dtype)
    col = ib * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1
    )
    return jnp.where(col < v, logits.astype(jnp.float32), NEG_INF), col


def linear_cross_entropy(
    x: jax.Array,  # [N, E] final hidden states (post final norm)
    w: jax.Array,  # head weight: [V, E] ("ve", gpt2 tied wte) or [E, V] ("ev")
    targets: jax.Array,  # [N] int
    block_v: int = 8192,
    w_layout: str = "ve",
    logits_dtype=None,
) -> jax.Array:
    """Mean cross-entropy of softmax(x @ head) without materialising logits.

    Per vocab block: one MXU matmul whose [N, block_v] result feeds an
    online (m, l, gold) logsumexp update and dies — the block logits are
    rounded to ``logits_dtype`` (default: x.dtype) so the fused path
    reproduces the unfused head's ``cfg.logits_dtype`` numerics; the
    reductions run in f32. Backward recomputes each block's logits and
    feeds softmax-minus-onehot straight into the dx / dW matmuls.
    """
    if w_layout not in ("ve", "ev"):
        raise ValueError(f"w_layout must be 've' or 'ev', got {w_layout!r}")
    ldt = jnp.dtype(logits_dtype) if logits_dtype is not None else None
    return _linear_ce_op(block_v, w_layout, ldt)(x, w, targets)


@functools.lru_cache(maxsize=None)
def _linear_ce_op(block_v: int, w_layout: str, logits_dtype):
    """custom_vjp op over (x, w, targets); block_v / w_layout are static."""

    @jax.custom_vjp
    def op(x, w, targets):
        loss, _ = _fwd(x, w, targets)
        return loss

    def _pad(wc):
        v = wc.shape[0] if w_layout == "ve" else wc.shape[1]
        nb = -(-v // block_v)
        pad_v = nb * block_v - v
        pad = ((0, pad_v), (0, 0)) if w_layout == "ve" else ((0, 0), (0, pad_v))
        return jnp.pad(wc, pad), v, nb

    def _slice(wp, ib):
        e = wp.shape[1] if w_layout == "ve" else wp.shape[0]
        if w_layout == "ve":
            return jax.lax.dynamic_slice(wp, (ib * block_v, 0), (block_v, e))
        return jax.lax.dynamic_slice(wp, (0, ib * block_v), (e, block_v))

    def _fwd(x, w, targets):
        n = x.shape[0]
        ldt = logits_dtype or x.dtype
        wc = w.astype(x.dtype)
        wp, v, nb = _pad(wc)

        def body(carry, ib):
            m, l, gold = carry
            wblk = _slice(wp, ib)
            logits, col = _block_logits(
                x, wblk, ib, block_v, v, ldt, w_layout
            )
            m_new = jnp.maximum(m, logits.max(axis=1))
            l = l * jnp.exp(m - m_new) + jnp.exp(
                logits - m_new[:, None]
            ).sum(axis=1)
            hit = col == targets[:, None]
            gold = gold + jnp.where(hit, logits, 0.0).sum(axis=1)
            return (m_new, l, gold), None

        (m, l, gold), _ = jax.lax.scan(
            body,
            tuple(
                _vary_like(z, x, wc, targets)
                for z in (
                    jnp.full((n,), NEG_INF, jnp.float32),
                    jnp.zeros((n,), jnp.float32),
                    jnp.zeros((n,), jnp.float32),
                )
            ),
            jnp.arange(nb),
        )
        logz = m + jnp.log(l)
        loss = jnp.mean(logz - gold)
        # Zero-size dtype token: dw must come back in w's dtype, but only
        # the bf16-cast wc is saved.
        return loss, (x, wc, targets, logz, jnp.zeros((), w.dtype))

    def _bwd(res, ct):
        import numpy as np

        x, wc, targets, logz, w_dtype_token = res
        n = x.shape[0]
        ldt = logits_dtype or x.dtype
        wp, v, nb = _pad(wc)
        scale = ct / n

        def body(carry, ib):
            dx_acc, dw_acc = carry
            wblk = _slice(wp, ib)
            logits, col = _block_logits(
                x, wblk, ib, block_v, v, ldt, w_layout
            )
            p = jnp.exp(logits - logz[:, None])  # pad cols: exp(-inf) = 0
            p = p - (col == targets[:, None]).astype(jnp.float32)
            dl = (p * scale).astype(x.dtype)  # [N, bv]
            if w_layout == "ve":
                dx_dims = (((1,), (0,)), ((), ()))
                dwblk = jax.lax.dot_general(
                    dl, x, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [bv, E]
                at = (ib * block_v, 0)
            else:
                dx_dims = (((1,), (1,)), ((), ()))
                dwblk = jax.lax.dot_general(
                    x, dl, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [E, bv]
                at = (0, ib * block_v)
            dx_acc = dx_acc + jax.lax.dot_general(
                dl, wblk, dx_dims, preferred_element_type=jnp.float32
            )
            dw_acc = jax.lax.dynamic_update_slice(dw_acc, dwblk, at)
            return (dx_acc, dw_acc), None

        (dx, dwp), _ = jax.lax.scan(
            body,
            tuple(
                _vary_like(z, x, wc, targets, ct)
                for z in (
                    jnp.zeros(x.shape, jnp.float32),
                    jnp.zeros(wp.shape, jnp.float32),
                )
            ),
            jnp.arange(nb),
        )
        dw = (dwp[:v] if w_layout == "ve" else dwp[:, :v]).astype(
            w_dtype_token.dtype
        )
        return (
            dx.astype(x.dtype),
            dw,
            np.zeros(targets.shape, jax.dtypes.float0),
        )

    op.defvjp(_fwd, _bwd)
    return op
