"""Loss functions.

The reference computes flat next-token cross-entropy over all positions
(reference train/trainer.py:53-56: F.cross_entropy on [B*T, V] logits vs
[B*T] targets). Same semantics here, in float32, via log-softmax gather —
no [B*T, V] one-hot materialisation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean token cross-entropy. logits [..., V] float; targets [...] int."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    ).squeeze(-1)
    return jnp.mean(logz - gold)
