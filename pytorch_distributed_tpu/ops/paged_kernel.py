"""Pallas TPU paged-attention decode kernel (+ XLA gather fallback).

Single-query attention for the paged serving engine
(serving/engine.PagedBatchedDecodeEngine): each batch row's K/V lives in
fixed-size PAGES of a shared pool ``[P, page, Hkv, D]``, addressed
through a per-row block table — the vLLM cache layout, which is what
lets ``slots`` scale with the pool instead of ``slots x max_len``
(ROADMAP direction 1; serving practice surveyed in PAPERS.md #1).

The kernel is the piece that makes per-row attention cost scale with the
row's DEPTH instead of ``max_len``:

- grid ``(B, Hkv, n_pages)`` with the page dimension innermost and
  sequential (online-softmax accumulator state lives in VMEM scratch
  across it);
- the block tables and per-row lengths ride ``PrefetchScalarGridSpec``
  scalar prefetch, so the K/V BlockSpec *index maps* resolve
  ``tables[b, i]`` before the body runs — the page "gather" is just the
  kernel's own DMA picking its source block, never a materialised
  [B, max_len] copy;
- pages past a row's depth are skipped with ``pl.when`` (no MXU work,
  and their DMA re-reads the row's last useful page id — the host fills
  unallocated table entries with the scratch page 0, so the skipped
  fetch is bounded and harmless);
- grouped-query heads share their KV head inside the kernel: the grid
  walks KV heads and each step computes the whole ``group = H // Hkv``
  query-head block against one [page, D] key block.

GQA + per-row depth masking match ``models/decode._cached_attention``'s
masked-softmax math up to online-softmax reassociation (floating-point
reordering only — the equivalence test pins allclose, and engine-level
token equality is pinned separately on the gather path).

Off-TPU (this repo's CPU rig) the kernel runs in INTERPRET mode — the
dispatcher defaults to it automatically — and the serving engine's
default paged attention is the pure-XLA ``gather_pages`` fallback in
models/decode.py, which is bit-identical to the dense engine's math (the
property the paged-vs-dense token-equality pins rely on). Read
/opt/skills/guides/pallas_guide.md before touching the kernel body.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_tpu.ops.flash_kernel import _compiler_params

NEG_INF = -1e30  # finite mask (matches ops/attention.py): -inf NaNs softmax


def _paged_kernel(
    tables_ref,  # [B, n_pages] int32 (scalar prefetch)
    lens_ref,  # [B] int32 (scalar prefetch): row's query position
    q_ref,  # [1, group, D]
    k_ref,  # [1, page, 1, D] — the page tables_ref[b, i], head h
    v_ref,  # [1, page, 1, D]
    o_ref,  # [1, group, D]
    acc_sc,  # [group, D] f32
    m_sc,  # [group, 1] f32
    l_sc,  # [group, 1] f32
    *,
    page: int,
    n_pages: int,
    scale: float,
):
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc[:])
        m_sc[:] = jnp.full_like(m_sc[:], NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc[:])

    length = lens_ref[b]  # keys 0..length (inclusive) are valid

    # Pages wholly past the row's depth do no work: the decode cost of a
    # short row is its own page count, not max_len.
    @pl.when(i * page <= length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [group, D]
        kb = k_ref[0, :, 0, :].astype(jnp.float32)  # [page, D]
        vb = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group, page]
        kpos = i * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(kpos <= length, s, NEG_INF)
        m_new = jnp.maximum(m_sc[:], jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_sc[:] - m_new)
        l_sc[:] = l_sc[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:] = m_new

    @pl.when(i == n_pages - 1)
    def _emit():
        o_ref[0] = (
            acc_sc[:] / jnp.maximum(l_sc[:], 1e-30)
        ).astype(o_ref.dtype)


def _paged_kernel_q8(
    tables_ref,  # [B, n_pages] int32 (scalar prefetch)
    lens_ref,  # [B] int32 (scalar prefetch): row's query position
    q_ref,  # [1, group, D]
    k_ref,  # [1, page, 1, D] int8 — the page tables_ref[b, i], head h
    v_ref,  # [1, page, 1, D] int8
    ks_ref,  # [1, page, 1] f32 per-token K scales for the same page/head
    vs_ref,  # [1, page, 1] f32
    o_ref,  # [1, group, D]
    acc_sc,  # [group, D] f32
    m_sc,  # [group, 1] f32
    l_sc,  # [group, 1] f32
    *,
    page: int,
    n_pages: int,
    scale: float,
):
    """The int8 twin of ``_paged_kernel``: identical online-softmax
    structure, but the page DMA moves INT8 K/V blocks plus their
    per-token f32 scales, and dequantization happens in VMEM right
    before the dot — HBM traffic for a page drops to (D + 4)/(4D) of
    the f32 kernel's. Numerics past the dequant are the f32 kernel's
    exactly (same accumulator dtypes, same masking), so quantized-vs-
    gather equivalence is pinned the same way (tests/test_quant.py)."""
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc[:])
        m_sc[:] = jnp.full_like(m_sc[:], NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc[:])

    length = lens_ref[b]

    @pl.when(i * page <= length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [group, D]
        # Dequant-in-kernel: int8 page block * per-token scale column.
        kb = k_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, :, 0][:, None]
        vb = v_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group, page]
        kpos = i * page + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(kpos <= length, s, NEG_INF)
        m_new = jnp.maximum(m_sc[:], jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_sc[:] - m_new)
        l_sc[:] = l_sc[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:] = m_new

    @pl.when(i == n_pages - 1)
    def _emit():
        o_ref[0] = (
            acc_sc[:] / jnp.maximum(l_sc[:], 1e-30)
        ).astype(o_ref.dtype)


# repolint: allow(jit-donation-decision) — functional attention op: the
# K/V pages belong to the serving engine's donated cache (aliased at the
# PROGRAM boundary, not here) and q is read by the caller's residual.
@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_call(q, k_pages, v_pages, block_tables, lengths, interpret):
    b, h, d = q.shape
    n_pages = block_tables.shape[1]
    page, hkv = k_pages.shape[1], k_pages.shape[2]
    group = h // hkv
    kernel = functools.partial(
        _paged_kernel,
        page=page, n_pages=n_pages, scale=1.0 / (d**0.5),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, n_pages),
        in_specs=[
            pl.BlockSpec(
                (1, group, d), lambda bi, hi, i, tables, lens: (bi, hi, 0)
            ),
            pl.BlockSpec(
                (1, page, 1, d),
                lambda bi, hi, i, tables, lens: (tables[bi, i], 0, hi, 0),
            ),
            pl.BlockSpec(
                (1, page, 1, d),
                lambda bi, hi, i, tables, lens: (tables[bi, i], 0, hi, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, group, d), lambda bi, hi, i, tables, lens: (bi, hi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
        **_compiler_params(),
    )(block_tables, lengths, q, k_pages, v_pages)


# repolint: allow(jit-donation-decision) — functional attention op, same
# aliasing story as _paged_call (the pool is donated at the engine
# program boundary, never here).
@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_call_q8(q, k_pages, v_pages, k_scales, v_scales,
                   block_tables, lengths, interpret):
    b, h, d = q.shape
    n_pages = block_tables.shape[1]
    page, hkv = k_pages.shape[1], k_pages.shape[2]
    group = h // hkv
    kernel = functools.partial(
        _paged_kernel_q8,
        page=page, n_pages=n_pages, scale=1.0 / (d**0.5),
    )
    page_spec = pl.BlockSpec(
        (1, page, 1, d),
        lambda bi, hi, i, tables, lens: (tables[bi, i], 0, hi, 0),
    )
    scale_spec = pl.BlockSpec(
        (1, page, 1),
        lambda bi, hi, i, tables, lens: (tables[bi, i], 0, hi),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, n_pages),
        in_specs=[
            pl.BlockSpec(
                (1, group, d), lambda bi, hi, i, tables, lens: (bi, hi, 0)
            ),
            page_spec,
            page_spec,
            scale_spec,
            scale_spec,
        ],
        out_specs=pl.BlockSpec(
            (1, group, d), lambda bi, hi, i, tables, lens: (bi, hi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
        **_compiler_params(),
    )(block_tables, lengths, q, k_pages, v_pages, k_scales, v_scales)


def paged_decode_attention(
    q: jax.Array,  # [B, H, D] — ONE query token per row
    k_pages: jax.Array,  # [P, page, Hkv, D] (int8 when quantized)
    v_pages: jax.Array,  # [P, page, Hkv, D]
    block_tables: jax.Array,  # [B, n_pages] int32 page ids
    lengths: jax.Array,  # [B] int32: the row's position (keys <= it valid)
    *,
    k_scales: jax.Array | None = None,  # [P, page, Hkv] f32 (int8 pages)
    v_scales: jax.Array | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Paged single-query attention, [B, H, D] -> [B, H, D]. ``lengths``
    is each row's query position: key j is attended iff j <= lengths[b]
    (the dense decode-step mask at T=1). ``interpret=None`` picks the
    compiled kernel on TPU and interpreter mode elsewhere.

    ``k_scales``/``v_scales`` switch to the int8 kernel: pages are int8
    with per-token/per-head f32 scales and dequantization happens in
    VMEM (the bandwidth-bound read moves quarter-width pages)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    h, hkv = q.shape[1], k_pages.shape[2]
    if h % hkv:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {hkv}"
        )
    if (k_scales is None) != (v_scales is None):
        raise ValueError(
            "k_scales and v_scales must be given together (int8 pages) "
            "or both omitted (full-precision pages)"
        )
    if k_scales is not None:
        return _paged_call_q8(
            q, k_pages, v_pages, k_scales, v_scales,
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(lengths, jnp.int32),
            bool(interpret),
        )
    return _paged_call(
        q, k_pages, v_pages,
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(lengths, jnp.int32),
        bool(interpret),
    )


def paged_decode_attention_reference(
    q, k_pages, v_pages, block_tables, lengths,
    k_scales=None, v_scales=None,
) -> jax.Array:
    """Pure-XLA reference: gather the per-row page view (dequantizing it
    when scale pools are given) and run the dense masked-softmax math
    (models/decode._cached_attention's paged gather branch, restated at
    the T=1 shape) — what the kernel is equivalence-tested against."""
    from pytorch_distributed_tpu.models.decode import gather_pages

    b, h, d = q.shape
    tables = jnp.asarray(block_tables, jnp.int32)
    ck = gather_pages(k_pages, tables)
    cv = gather_pages(v_pages, tables)
    if k_scales is not None:
        from pytorch_distributed_tpu.ops.quant import dequantize_kv

        ck = dequantize_kv(ck, gather_pages(k_scales, tables), q.dtype)
        cv = dequantize_kv(cv, gather_pages(v_scales, tables), q.dtype)
    s = ck.shape[1]
    hkv = ck.shape[2]
    if hkv != h:
        rep = h // hkv
        ck = jnp.repeat(ck, rep, axis=2)
        cv = jnp.repeat(cv, rep, axis=2)
    scores = jnp.einsum(
        "bhd,bshd->bhs", q, ck, preferred_element_type=jnp.float32
    ) / (d**0.5)
    kpos = jnp.arange(s, dtype=jnp.int32)
    valid = kpos[None, None, :] <= jnp.asarray(lengths, jnp.int32)[
        :, None, None
    ]
    scores = jnp.where(valid, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhs,bshd->bhd", w.astype(cv.dtype), cv
    ).astype(q.dtype)
