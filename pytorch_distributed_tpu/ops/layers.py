"""Core layer primitives as pure functions over param dicts.

Conventions:
- Dense kernels are stored ``[in_features, out_features]`` — the natural
  layout for ``x @ W`` on the MXU. (The torch reference stores nn.Linear
  weights ``[out, in]`` and has to transpose HF Conv1D weights on import,
  reference my_gpt2.py:254-280; in this layout HF GPT-2 Conv1D weights import
  transpose-free.)
- Normalisation statistics are computed in float32 regardless of the
  activation dtype, then cast back (bf16-safe).
- Dropout takes an explicit PRNG key; ``deterministic=True`` or rate 0 is a
  no-op that traces to nothing under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.tp import tp_reduce


def dense(
    x: jax.Array, params: dict, *, precision=None, tp_reduce_axis=None,
    extra_pre_reduce: jax.Array | None = None,
) -> jax.Array:
    """y = x @ kernel + bias. kernel: [in, out]; bias optional.

    ``tp_reduce_axis``: name of a shard_map tensor axis this matmul is
    row-parallel over — the kernel's input dim is sharded, each shard
    computes a partial sum, and the psum (ops/tp.tp_reduce) runs BEFORE the
    (replicated) bias is added so the bias is counted once.

    ``extra_pre_reduce``: an addend joined to the (possibly partial)
    matmul output BEFORE the tp psum — the per-row LoRA delta path
    (models/decode.lora_delta): on a row-parallel projection the delta
    is itself a per-shard partial, and linearity means summing
    (base + delta) partials in ONE psum equals psumming each — the
    pinned TP collective counts are untouched by adapters.

    A quantized kernel (ops/quant.quantize_weight dict: int8 values +
    per-out-channel f32 scale) runs through the same ``ops.quant.qdot``
    the llama raw matmuls use — upcast in-register, scale applied to
    the local output BEFORE the tp psum (the scale is a linear factor,
    so reducing scaled partials equals scaling the reduction and the
    pinned TP all-reduce counts survive weight quantization by
    construction).
    """
    from pytorch_distributed_tpu.ops.quant import qdot

    y = qdot(x, params["kernel"], precision=precision)
    if extra_pre_reduce is not None:
        y = y + extra_pre_reduce.astype(y.dtype)
    if tp_reduce_axis is not None:
        y = tp_reduce(y, tp_reduce_axis)
    bias = params.get("bias")
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def layer_norm(x: jax.Array, params: dict, *, eps: float) -> jax.Array:
    """LayerNorm with learned scale/bias (reference my_gpt2.py:110-118)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm(x: jax.Array, params: dict, *, eps: float) -> jax.Array:
    """RMSNorm (llama family)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    y = y * params["scale"].astype(jnp.float32)
    return y.astype(x.dtype)


def dropout(
    x: jax.Array,
    rate: float,
    key: jax.Array | None,
    *,
    deterministic: bool,
) -> jax.Array:
    if deterministic or rate == 0.0:
        return x
    if key is None:
        raise ValueError("dropout requires a PRNG key when not deterministic")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, p=keep, shape=x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


_ACTIVATIONS = {
    # "gelu_new" is HF's tanh-approximated gelu — what ACT2FN resolves to for
    # GPT-2 (reference my_gpt2.py:90 via transformers.activations).
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


def activation(name: str):
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KeyError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from None
