"""Ulysses (DeepSpeed-style) all-to-all sequence parallelism.

The second canonical long-context technique next to ring attention
(ops/ring_attention.py). Where ring keeps the sequence sharded and streams
KV blocks around the ring (n - 1 ppermute hops, online-softmax merging),
Ulysses re-shards ONCE per attention call:

    [B, T/n, H, D]  --all_to_all-->  [B, T, H/n, D]
    full-sequence attention on the local head group (any backend)
    [B, T, H/n, D]  --all_to_all-->  [B, T/n, H, D]

Two all-to-alls (plus two for K/V) move the same bytes a ring moves in
total, but as one balanced shuffle instead of n-1 dependent hops — the
standard trade: Ulysses needs H divisible by the mesh axis and its
collective pattern loves full-bisection fabrics; ring only needs T
divisible and tolerates skinny rings. Inside shard_map the local attention
sees the FULL sequence, so the math (causal mask, softmax) is exactly the
single-device computation — no online merging, and AD differentiates the
all-to-alls natively (their transpose is the reverse all-to-all).

GQA: KV heads are scattered the same way, so n must divide the KV head
count too (repeat_kv first if it does not — the caller's choice).
"""

from __future__ import annotations

import warnings

import jax

def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T_local, H, D] -> [B, T_global, H_local, D]."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T_global, H_local, D] -> [B, T_local, H, D]."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,  # [B, T_local, H, D] (sequence-sharded over axis_name)
    k: jax.Array,  # [B, T_local, Hkv, D]
    v: jax.Array,  # [B, T_local, Hkv, D]
    *,
    axis_name: str,
    causal: bool = True,
    impl: str = "naive",
    dropout_rate: float = 0.0,
    dropout_key: jax.Array | None = None,
    deterministic: bool = True,
) -> jax.Array:
    """Sequence-parallel attention via head/sequence all-to-all re-sharding.

    Must run inside shard_map with the T dim sharded over ``axis_name``.
    Returns [B, T_local, H, D] with the same sharding as ``q``. ``impl``
    picks the LOCAL full-sequence backend: "flash" (blockwise/Pallas,
    O(T) memory — what long context needs) or "naive" (O(T^2) scores).

    Attention dropout: after the re-shard the local weights cover the
    FULL sequence for this shard's own head group, so a mask drawn from
    a per-shard key is single-device dropout on those heads. The shard's
    axis index is folded into ``dropout_key`` HERE (self-contained — a
    replicated caller key would otherwise give every head group the
    identical mask, correlated in a way the single-device [B, H, T, T]
    draw never is; the extra fold on the already-per-shard keys the
    shard_map training paths pass is statistically harmless). Head groups
    on different shards therefore draw INDEPENDENT masks — together
    statistically equivalent to the single-device draw. The local backend
    falls back to naive when dropout is active (flash has no dropout
    support — the same fallback the single-device dispatch makes).
    """
    n = jax.lax.psum(1, axis_name)
    h, hkv = q.shape[2], k.shape[2]
    if h % n or hkv % n:
        raise ValueError(
            f"ulysses needs the mesh axis ({n}) to divide both head counts "
            f"(H={h}, Hkv={hkv}); use ring attention (or repeat KV heads) "
            "otherwise"
        )
    qh = _heads_to_seq(q, axis_name)  # [B, T, H/n, D]
    kh = _heads_to_seq(k, axis_name)
    vh = _heads_to_seq(v, axis_name)
    # Full-sequence attention on the local head group — exactly the
    # single-device math (GQA group structure is preserved: H/n query
    # heads over Hkv/n KV heads keeps the same group size).
    dropout_active = not deterministic and dropout_rate > 0.0
    if impl == "flash" and dropout_active:
        # Loud, not silent: at the sequence lengths Ulysses exists for,
        # the O(T^2) score matrix this fallback materialises can OOM or
        # regress sharply with no other runtime signal.
        warnings.warn(
            "ulysses_attention: impl='flash' with active attention "
            "dropout falls back to NAIVE attention (flash has no dropout "
            f"support) — O(T^2) score memory at T={q.shape[1] * n} "
            "global sequence length; set attn_pdrop=0.0 to keep flash",
            stacklevel=2,
        )
    if dropout_active and dropout_key is not None:
        dropout_key = jax.random.fold_in(
            dropout_key, jax.lax.axis_index(axis_name)
        )
    if impl == "flash" and not dropout_active:
        from pytorch_distributed_tpu.ops.pallas_flash import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal)
    else:
        from pytorch_distributed_tpu.ops.attention import naive_attention

        out = naive_attention(
            qh, kh, vh,
            causal=causal,
            dropout_rate=dropout_rate,
            dropout_key=dropout_key,
            deterministic=deterministic,
        )
    return _seq_to_heads(out, axis_name)
