from pytorch_distributed_tpu.ops.attention import multi_head_attention  # noqa: F401
from pytorch_distributed_tpu.ops.layers import (  # noqa: F401
    dense,
    dropout,
    layer_norm,
    rms_norm,
)
from pytorch_distributed_tpu.ops.remat import apply_remat  # noqa: F401
