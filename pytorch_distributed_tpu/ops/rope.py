"""Rotary position embeddings (llama family).

Half-split convention (matches HF Llama): the head dim is split into two
halves, rotate_half([x1, x2]) = [-x2, x1], and
x_rot = x*cos + rotate_half(x)*sin with angles pos / theta^(2i/d).
Angles are computed in float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(
    seq_len: int, head_dim: int, theta: float, *, offset=0
) -> tuple[jax.Array, jax.Array]:
    """Returns (cos, sin), each [seq_len, head_dim] float32. ``offset`` may be
    a traced scalar (e.g. a sequence-shard start under context parallelism)
    or a [B, 1] per-row column (slot-batched decode, where every batch row
    sits at its own position): broadcasting then yields [B, seq_len,
    head_dim] angles whose row b equals the scalar-offset result for
    offset[b]."""
    half = head_dim // 2
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) * 2.0 / head_dim)
    )
    pos = jnp.arange(seq_len, dtype=jnp.float32) + offset  # [T] or [B, T]
    angles = pos[..., None] * inv_freq  # [..., T, half]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [..., T, D]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jax.Array,  # [B, T, H, D]
    cos: jax.Array,  # [T, D] shared, or [B, T, D] per-row angles
    sin: jax.Array,
) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    if cos.ndim == 3:  # per-row positions (slot-batched decode)
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
    else:
        c = cos[None, :, None, :]
        s = sin[None, :, None, :]
    return (x32 * c + _rotate_half(x32) * s).astype(dtype)
