"""Hand-tiled Pallas TPU flash attention: fwd + fused one-pass backward.

This replaces the library kernel (jax.experimental.pallas.ops.tpu.
flash_attention) on the hot path. Three structural wins, all measured on
GPT-2 124M B=8 T=1024 (see benchmarks/PERF_NOTES.md):

- **One-pass backward.** The library runs two backward kernels (dkv, then
  dq), each re-computing the score matrix from scratch — 7 block-level
  matmuls per (q, k) block pair. The fused kernel computes scores once and
  produces dq, dk, dv together: 5 matmuls, one pass over the blocks.
- **Compact softmax residual.** The library emits l and m as lane-broadcast
  [B, H, T, 128] f32 tensors; saved by the remat policy they cost ~100 MB
  of HBM write+read per layer at bench shapes. Here the forward emits ONE
  combined logsumexp, sliced to a compact [B, H, T] residual right after
  the kernel (the kernel-side write stays lane-broadcast — Mosaic block
  shapes need an aligned minor dim — but the padded copy dies immediately
  and only the compact slice is saved / re-read).
- **K/V resident in VMEM.** The key/value tensors for one (batch, head) fit
  VMEM at any practical T (2 x T x D bf16), so the forward's key-block loop
  streams scores without re-fetching K/V from HBM.

The backward works in TRANSPOSED score space (s_T [bk, bq]: keys on
sublanes, queries on lanes) so the per-query logsumexp/delta rows enter as
[1, bq] lane vectors that broadcast across sublanes — no in-kernel
transposes anywhere. dq is accumulated in a VMEM-resident f32 output block
revisited across the (innermost) key-block grid dimension.

Grouped-query attention is served by BlockSpec index maps (query head h
reads KV head h // group) — no materialized head repeat. The backward
emits per-query-head dk/dv and group-sums them outside the kernel.

Softmax runs in the base-2 domain (exp2 is cheaper than exp on the VPU;
the log2(e) factor folds into the score scale).

Layout convention: [B, H, T, D] (callers transpose from the model's
[B, T, H, D]; XLA fuses that into neighbouring ops). Causal masking is for
T == S self-attention.

Capability anchor: the reference names torch's flash/SDPA kernels as its
compute-intensive ops (reference model/pytorch_utils.py:9-13) without ever
calling one; here the kernel is a first-class implementation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LOG2E = 1.4426950408889634  # log2(e): natural-domain scores -> exp2 domain
LN2 = 0.6931471805599453
NEG_INF = -1e30  # finite; -inf would turn all-masked rows into NaNs

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512

_LANES = 128
_SUBLANES = 8


def _pick_block(t: int, preferred: int) -> int:
    for c in (preferred, 512, 256, 128):
        if c <= preferred and t % c == 0:
            return c
    return t


def _compiler_params(vmem_limit_bytes: int | None = None):
    # b and h grid dims are independent; the innermost dim carries
    # sequential state (fwd: resident K/V reuse; bwd: dq accumulation).
    kw = {"dimension_semantics": ("parallel", "parallel", "arbitrary")}
    if vmem_limit_bytes is not None:
        kw["vmem_limit_bytes"] = vmem_limit_bytes
    # Staged fallback across jax-version signature drift: losing the new
    # vmem kwarg must not silently drop dimension_semantics with it.
    while kw:
        try:
            return {"compiler_params": pltpu.CompilerParams(**kw)}
        except (TypeError, AttributeError):
            kw.pop(sorted(kw)[-1])  # vmem_limit_bytes first, then the rest
    return {}


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,  # [1, 1, bq, D]
    k_ref,  # [1, 1, T, D] (resident per (b, h))
    v_ref,  # [1, 1, T, D]
    o_ref,  # [1, 1, bq, D]
    lse_ref,  # [1, 1, bq, 128] f32 (lane-broadcast; sliced outside)
    acc_sc,  # [bq, D] f32
    m_sc,  # [bq, 1] f32
    l_sc,  # [bq, 1] f32
    *,
    bq: int,
    bk: int,
    nk: int,
    scale: float,
    causal: bool,
):
    iq = pl.program_id(2)
    q = q_ref[0, 0]
    m_sc[:] = jnp.full_like(m_sc[:], NEG_INF)
    l_sc[:] = jnp.zeros_like(l_sc[:])
    acc_sc[:] = jnp.zeros_like(acc_sc[:])
    s_scale = scale * LOG2E

    def body(ik, _):
        kb = k_ref[0, 0, pl.ds(ik * bk, bk), :]
        vb = v_ref[0, 0, pl.ds(ik * bk, bk), :]
        s = (
            jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * s_scale
        )  # [bq, bk], base-2 domain

        if causal:
            # Only diagonal-straddling blocks need the elementwise mask;
            # strictly-future blocks were excluded by the loop bound.
            def masked(s):
                qpos = iq * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0
                )
                kpos = ik * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1
                )
                return jnp.where(kpos <= qpos, s, NEG_INF)

            s = jax.lax.cond(
                ik * bk + bk - 1 > iq * bq, masked, lambda s: s, s
            )

        m_prev = m_sc[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_sc[:] = l_sc[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:] = m_new
        return 0

    # Causal: skip key blocks strictly past this query block.
    kmax = pl.cdiv((iq + 1) * bq, bk) if causal else nk
    jax.lax.fori_loop(0, kmax, body, 0)

    l = jnp.maximum(l_sc[:], 1e-30)  # causal self-attn never all-masks a row
    o_ref[0, 0] = (acc_sc[:] / l).astype(o_ref.dtype)
    lse = m_sc[:] * LN2 + jnp.log(l)  # [bq, 1], natural-log domain
    lse_ref[0, 0] = jnp.broadcast_to(lse, (bq, _LANES))


def _fwd_call(q, k, v, causal, scale, bq, bk, interpret):
    b, hq, t, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    nq, nk = t // bq, t // bk

    kernel = functools.partial(
        _fwd_kernel, bq=bq, bk=bk, nk=nk, scale=scale, causal=causal
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq),
        in_specs=[
            pl.BlockSpec(
                (1, 1, bq, d), lambda b, h, iq: (b, h, iq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, t, d), lambda b, h, iq: (b, h // group, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, t, d), lambda b, h, iq: (b, h // group, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, bq, d), lambda b, h, iq: (b, h, iq, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bq, _LANES), lambda b, h, iq: (b, h, iq, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, t, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
        **_compiler_params(),
    )(q, k, v)
    # Compact residual: the padded copy is dead after this slice.
    return o, lse[..., 0]


# --------------------------------------------------------------------------
# fused backward: one pass produces dq, dk, dv
# --------------------------------------------------------------------------


def _bwd_kernel(
    q_ref,  # [1, 1, T, D] (resident per (b, h))
    k_ref,  # [1, 1, bk, D]
    v_ref,  # [1, 1, bk, D]
    do_ref,  # [1, 1, T, D] (resident)
    lse_ref,  # [1, 1, 8, T] f32 (resident; sublane-broadcast, base-e)
    delta_ref,  # [1, 1, 8, T] f32 (resident; rowsum(o * do))
    dq_ref,  # [1, 1, T, D] f32 — revisited across ik, accumulated
    dk_ref,  # [1, 1, bk, D] f32 (per QUERY head; group-summed outside)
    dv_ref,  # [1, 1, bk, D] f32
    dk_sc,  # [bk, D] f32
    dv_sc,  # [bk, D] f32
    *,
    bq: int,
    bk: int,
    nq: int,
    scale: float,
    causal: bool,
):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _():
        dq_ref[:] = jnp.zeros_like(dq_ref[:])

    kb = k_ref[0, 0]
    vb = v_ref[0, 0]
    dk_sc[:] = jnp.zeros_like(dk_sc[:])
    dv_sc[:] = jnp.zeros_like(dv_sc[:])
    s_scale = scale * LOG2E

    def body(iq, _):
        qb = q_ref[0, 0, pl.ds(iq * bq, bq), :]
        dob = do_ref[0, 0, pl.ds(iq * bq, bq), :]
        # [1, bq] lane rows — broadcast across the bk sublanes of s_t.
        lse_row = lse_ref[0, 0, :1, pl.ds(iq * bq, bq)] * LOG2E
        delta_row = delta_ref[0, 0, :1, pl.ds(iq * bq, bq)]
        # Transposed scores: keys on sublanes, queries on lanes.
        s_t = (
            jax.lax.dot_general(
                kb, qb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * s_scale
        )  # [bk, bq]

        if causal:

            def masked(s_t):
                kpos = ik * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bk, bq), 0
                )
                qpos = iq * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bk, bq), 1
                )
                return jnp.where(kpos <= qpos, s_t, NEG_INF)

            s_t = jax.lax.cond(
                ik * bk + bk - 1 > iq * bq, masked, lambda s: s, s_t
            )

        p_t = jnp.exp2(s_t - lse_row)  # already normalized (lse is global)
        dp_t = jax.lax.dot_general(
            vb, dob, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, bq]
        ds_t = p_t * (dp_t - delta_row) * scale  # grad wrt raw scores
        p_b = p_t.astype(do_ref.dtype)
        ds_b = ds_t.astype(q_ref.dtype)
        dv_sc[:] += jax.lax.dot_general(
            p_b, dob, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # contract bq: [bk, D]
        dk_sc[:] += jax.lax.dot_general(
            ds_b, qb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # contract bq: [bk, D]
        dq_ref[0, 0, pl.ds(iq * bq, bq), :] += jax.lax.dot_general(
            ds_b, kb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # contract bk: [bq, D]
        return 0

    # Causal: query blocks strictly before this key block contribute nothing.
    iq_start = (ik * bk) // bq if causal else 0
    jax.lax.fori_loop(iq_start, nq, body, 0)
    dk_ref[0, 0] = dk_sc[:]
    dv_ref[0, 0] = dv_sc[:]


def _bwd_call(q, k, v, do, lse, delta, causal, scale, bq, bk, interpret):
    b, hq, t, d = q.shape
    hkv = k.shape[1]
    group = hq // hkv
    nk = t // bk

    # Sublane-broadcast row stats ([B, H, T] -> [B, H, 8, T]) so blocks meet
    # Mosaic's (8, 128) minor-tile rule without any in-kernel retiling.
    lse8 = jnp.broadcast_to(lse[:, :, None, :], (b, hq, _SUBLANES, t))
    delta8 = jnp.broadcast_to(delta[:, :, None, :], (b, hq, _SUBLANES, t))

    kernel = functools.partial(
        _bwd_kernel, bq=bq, bk=bk, nq=t // bq, scale=scale, causal=causal
    )
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b, hq, nk),
        in_specs=[
            pl.BlockSpec(
                (1, 1, t, d), lambda b, h, ik: (b, h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, ik: (b, h // group, ik, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, ik: (b, h // group, ik, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, t, d), lambda b, h, ik: (b, h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, _SUBLANES, t), lambda b, h, ik: (b, h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, _SUBLANES, t), lambda b, h, ik: (b, h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, t, d), lambda b, h, ik: (b, h, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, ik: (b, h, ik, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda b, h, ik: (b, h, ik, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, t, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, t, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, t, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
        # The kernel keeps q/do (bf16) and the accumulating dq (f32)
        # resident per (b, h) — a footprint that scales with T, and
        # Mosaic's scheduling overheads scale with it too: the observed
        # scoped-vmem demand at llama3-1B T=8192 D=64 is ~17.5-33 MB
        # against the 16 MB default budget. Past T*D = 4096*64 raise the
        # per-kernel limit so long-context training compiles out of the
        # box; at or below it (every bench shape), leave the default
        # untouched so the measured schedules don't shift.
        **_compiler_params(
            vmem_limit_bytes=(
                96 * 1024 * 1024 if t * d > 4096 * 64 else None
            )
        ),
    )(q, k, v, do, lse8, delta8)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom VJP
# --------------------------------------------------------------------------


def flash_mha(
    q: jax.Array,  # [B, Hq, T, D]
    k: jax.Array,  # [B, Hkv, T, D]
    v: jax.Array,  # [B, Hkv, T, D]
    causal: bool = True,
    scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
):
    """Flash attention returning (o, lse).

    lse [B, Hq, T] f32 is a primal output on purpose: the remat "names"
    policy (ops/remat._flash_call_policy) saves every output of the
    underlying custom-VJP call, so with (o, lse) saved the backward runs
    only the fused gradient kernel — no forward re-run. lse is returned
    under ``stop_gradient``: it is a softmax *residual*, and this op does
    not define gradients through it (an lse-based regularizer would need
    its own VJP).
    """
    o, lse = _flash_mha_vjp(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return o, jax.lax.stop_gradient(lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_mha_vjp(
    q, k, v, causal, scale, block_q, block_k, interpret
):
    if q.shape[2] != k.shape[2] or k.shape != v.shape:
        raise ValueError(
            f"flash_mha requires T == S self-attention with matching K/V: "
            f"q {q.shape}, k {k.shape}, v {v.shape}"
        )
    bq = _pick_block(q.shape[2], block_q)
    bk = _pick_block(q.shape[2], block_k)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _fwd_call(q, k, v, causal, scale, bq, bk, interpret)


def _flash_mha_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_mha_vjp(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return (o, lse), (q, k, v, o, lse)


def _flash_mha_bwd(causal, scale, block_q, block_k, interpret, res, cts):
    q, k, v, o, lse = res
    do = cts[0]  # lse cotangent is structurally zero
    bq = _pick_block(q.shape[2], block_q)
    bk = _pick_block(q.shape[2], block_k)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    delta = jnp.sum(
        o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1
    )  # [B, Hq, T]
    dq, dk, dv = _bwd_call(
        q, k, v, do, lse, delta, causal, scale, bq, bk, interpret
    )
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:  # GQA: sum query-head grads within each KV group
        b, _, t, d = q.shape
        dk = dk.reshape(b, hkv, hq // hkv, t, d).sum(axis=2)
        dv = dv.reshape(b, hkv, hq // hkv, t, d).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_mha_vjp.defvjp(_flash_mha_fwd, _flash_mha_bwd)
