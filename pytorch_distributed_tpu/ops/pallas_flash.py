"""Flash (blockwise, online-softmax) causal attention.

The reference's attention materialises the full [B, H, T, T] score matrix
(reference my_gpt2.py:60-77) and lists torch's flash/efficient SDPA kernels as
compute-intensive save-targets (reference model/pytorch_utils.py:9-13) without
ever calling them. Here flash attention is a first-class implementation with
two backends behind one entry point:

- ``pallas``: this repo's hand-tiled Mosaic/Pallas TPU kernels
  (ops/flash_kernel.py) — K/V resident in VMEM, online softmax, compact
  [B, H, T] logsumexp residual, fused one-pass backward producing
  dq/dk/dv together. Used automatically on TPU when shapes are tileable.
  (The jax library kernel it replaced is kept importable below as
  ``_pallas_flash_olm`` for A/B measurement; it was ~2x slower in
  backward — two passes re-computing scores — and its lane-broadcast
  [B, H, T, 128] l/m stats cost ~100 MB/layer of remat save traffic.)
- ``blockwise``: a pure-XLA `lax.scan` over key blocks with the same
  online-softmax recurrence — O(T · block) memory, differentiable by
  ordinary AD. The portable fallback (CPU tests, ragged shapes).

GQA: the kernel maps query head h to KV head h // group via BlockSpec
index maps (no materialized repeat); the blockwise fallback repeats.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import NEG_INF, _repeat_kv
from pytorch_distributed_tpu.utils.compat import vma_of

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256

# The TPU kernel tiles the sequence into lane-width multiples; anything
# smaller (tiny test configs) takes the blockwise path.
_PALLAS_MIN_SEQ = 128


def _pallas_supported(t: int, s: int, d: int) -> bool:
    if jax.devices()[0].platform != "tpu":
        return False
    # t == s only: for S > T (decoding with a cache) the kernel masks
    # query i at absolute position i, whereas this module's convention aligns
    # the last query with the last key (q_offset = s - t) — the blockwise
    # path handles that case correctly.
    return (
        t == s
        and t % _PALLAS_MIN_SEQ == 0
        and d % 64 == 0
    )


def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Blockwise causal attention, [B, T, H, D] -> [B, T, H, D].

    Dispatches to the Pallas TPU kernel when running on TPU with tileable
    shapes, else to the portable scan implementation.
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    if _pallas_supported(t, s, d):
        return _pallas_flash(q, k, v, causal=causal)
    return blockwise_attention(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _pallas_flash_olm(q, k, v, causal, sm_scale, block_sizes):
    """Flash attention whose PRIMAL returns (o, l, m) — output plus the
    softmax statistics the backward kernels need.

    Exposing l/m as primal outputs (instead of hiding them inside the
    library custom_vjp's forward re-run) lets a remat policy save them:
    with (o, l, m) saved and q/k/v recomputable from the saved qkv
    projection, the backward pass runs ONLY the dq/dkv kernels — no
    second forward kernel launch. Measured ~5 ms/step on GPT-2 124M B=8.
    """
    import jax.experimental.pallas.ops.tpu.flash_attention as _lib

    o, l, m = _lib._flash_attention_impl(
        q, k, v, None, None, True, causal, sm_scale,
        block_sizes.block_b, block_sizes.block_q,
        block_sizes.block_k_major, block_sizes.block_k, False,
    )
    return o, l, m


def _pallas_flash_olm_fwd(q, k, v, causal, sm_scale, block_sizes):
    o, l, m = _pallas_flash_olm(q, k, v, causal, sm_scale, block_sizes)
    return (o, l, m), (q, k, v, o, l, m)


def _pallas_flash_olm_bwd(causal, sm_scale, block_sizes, res, cts):
    import jax.experimental.pallas.ops.tpu.flash_attention as _lib

    q, k, v, o, l, m = res
    do = cts[0]  # l/m are consumed by nothing differentiable: zero cotangents
    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    dk, dv = _lib._flash_attention_bwd_dkv(
        q, k, v, None, None, l, m, do, di,
        block_q_major=block_sizes.block_q_major_dkv,
        block_k_major=block_sizes.block_k_major_dkv,
        block_k=block_sizes.block_k_dkv,
        block_q=block_sizes.block_q_dkv,
        sm_scale=sm_scale, causal=causal,
        mask_value=_lib.DEFAULT_MASK_VALUE, debug=False,
    )
    dq, _ = _lib._flash_attention_bwd_dq(
        q, k, v, None, None, l, m, do, di,
        block_q_major=block_sizes.block_q_dq,
        block_k_major=block_sizes.block_k_major_dq,
        block_k=block_sizes.block_k_dq,
        sm_scale=sm_scale, causal=causal,
        mask_value=_lib.DEFAULT_MASK_VALUE, debug=False,
    )
    return dq, dk, dv


_pallas_flash_olm.defvjp(_pallas_flash_olm_fwd, _pallas_flash_olm_bwd)


def _pallas_flash(q, k, v, *, causal: bool) -> jax.Array:
    """[B, T, H, D] wrapper around the [B, H, T, D] Pallas TPU kernels
    (ops/flash_kernel.py). GQA heads are resolved inside the kernel via
    index maps — no repeat. The lse output is returned to the caller's
    jaxpr solely so the remat policy can save it (the value itself is
    only consumed by the custom VJP's backward)."""
    import os

    from pytorch_distributed_tpu.ops import flash_kernel

    out, _ = flash_kernel.flash_mha(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal,
        None,
        int(os.environ.get("PDT_FLASH_BQ", flash_kernel.DEFAULT_BLOCK_Q)),
        int(os.environ.get("PDT_FLASH_BK", flash_kernel.DEFAULT_BLOCK_K)),
    )
    return out.transpose(0, 2, 1, 3)


# repolint: allow(jit-donation-decision) — functional attention op:
# q/k/v belong to the caller and are read again in the backward pass.
@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k")
)
def blockwise_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Pure-XLA blockwise causal attention, [B, T, H, D] -> [B, T, H, D].

    Accumulators (running max m, normaliser l, output acc) are float32.
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    k = _repeat_kv(k, h // k.shape[2])
    v = _repeat_kv(v, h // v.shape[2])

    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k:
        # Fall back to one block covering the ragged dim (correct, less tiled).
        block_q = t if t % block_q else block_q
        block_k = s if s % block_k else block_k
    nq, nk = t // block_q, s // block_k

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # [B, H, nq, bq, D] layout so each scan step is a clean batched matmul.
    qb = q.transpose(0, 2, 1, 3).reshape(b, h, nq, block_q, d)
    kb = k.transpose(0, 2, 1, 3).reshape(b, h, nk, block_k, d)
    vb = v.transpose(0, 2, 1, 3).reshape(b, h, nk, block_k, d)

    q_offset = s - t  # query i sits at key position i + offset (S >= T)

    def per_q_block(iq, q_blk):
        """Online-softmax scan over key blocks for one query block."""
        q_start = iq * block_q + q_offset

        def kv_step(carry, inputs):
            acc, m, l = carry
            ik, k_blk, v_blk = inputs
            scores = (
                jnp.einsum(
                    "bhqd,bhkd->bhqk", q_blk, k_blk,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [B, H, bq, bk]
            if causal:
                qpos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                kpos = ik * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                scores = jnp.where(kpos <= qpos, scores, NEG_INF)
            m_new = jnp.maximum(m, scores.max(axis=-1))  # [B, H, bq]
            p = jnp.exp(scores - m_new[..., None])
            correction = jnp.exp(m - m_new)
            l_new = l * correction + p.sum(axis=-1)
            acc_new = acc * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32,
            )
            return (acc_new, m_new, l_new), None

        # Inside shard_map (e.g. as the Ulysses local backend) the scan
        # carry must vary on the same mesh axes as the activations.
        from pytorch_distributed_tpu.ops.tp import pvary_missing

        vma = tuple(vma_of(q_blk))
        acc0 = pvary_missing(
            jnp.zeros((b, h, block_q, d), jnp.float32), vma
        )
        m0 = pvary_missing(
            jnp.full((b, h, block_q), NEG_INF, jnp.float32), vma
        )
        l0 = pvary_missing(jnp.zeros((b, h, block_q), jnp.float32), vma)
        ks = jnp.arange(nk)
        (acc, m, l), _ = jax.lax.scan(
            kv_step,
            (acc0, m0, l0),
            (ks, kb.transpose(2, 0, 1, 3, 4), vb.transpose(2, 0, 1, 3, 4)),
        )
        # All-masked rows (can't happen for causal self-attention, where each
        # query sees at least itself) would give l=0; guard anyway.
        return acc / jnp.maximum(l, 1e-30)[..., None]

    out = jax.vmap(per_q_block, in_axes=(0, 2), out_axes=2)(
        jnp.arange(nq), qb
    )  # [B, H, nq, bq, D]
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)
