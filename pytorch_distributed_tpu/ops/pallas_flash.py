"""Flash (blockwise, online-softmax) causal attention.

The reference's attention materialises the full [B, H, T, T] score matrix
(reference my_gpt2.py:60-77) and lists torch's flash/efficient SDPA kernels as
compute-intensive save-targets (reference model/pytorch_utils.py:9-13) without
ever calling them. Here flash attention is a first-class implementation:
O(T · block) memory via the online-softmax recurrence, scanned over key
blocks with `lax.scan` so XLA keeps a small working set; differentiable by
ordinary AD (the scan is linearised — no hand-written VJP needed).

`flash_attention` is the stable entry point; a hand-tiled Pallas TPU kernel
(same signature, same math) plugs in behind it for the hot path — see
ops/pallas_flash_kernel.py once present.

GQA is supported by repeating KV heads, like the naive path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import NEG_INF, _repeat_kv

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k")
)
def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Blockwise causal attention, [B, T, H, D] -> [B, T, H, D].

    Accumulators (running max m, normaliser l, output acc) are float32.
    """
    b, t, h, d = q.shape
    s = k.shape[1]
    k = _repeat_kv(k, h // k.shape[2])
    v = _repeat_kv(v, h // v.shape[2])

    block_q = min(block_q, t)
    block_k = min(block_k, s)
    if t % block_q or s % block_k:
        # Fall back to one block covering the ragged dim (correct, less tiled).
        block_q = t if t % block_q else block_q
        block_k = s if s % block_k else block_k
    nq, nk = t // block_q, s // block_k

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # [B, H, nq, bq, D] layout so each scan step is a clean batched matmul.
    qb = q.transpose(0, 2, 1, 3).reshape(b, h, nq, block_q, d)
    kb = k.transpose(0, 2, 1, 3).reshape(b, h, nk, block_k, d)
    vb = v.transpose(0, 2, 1, 3).reshape(b, h, nk, block_k, d)

    q_offset = s - t  # query i sits at key position i + offset (S >= T)

    def per_q_block(iq, q_blk):
        """Online-softmax scan over key blocks for one query block."""
        q_start = iq * block_q + q_offset

        def kv_step(carry, inputs):
            acc, m, l = carry
            ik, k_blk, v_blk = inputs
            scores = (
                jnp.einsum(
                    "bhqd,bhkd->bhqk", q_blk, k_blk,
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [B, H, bq, bk]
            if causal:
                qpos = q_start + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                kpos = ik * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                scores = jnp.where(kpos <= qpos, scores, NEG_INF)
            m_new = jnp.maximum(m, scores.max(axis=-1))  # [B, H, bq]
            p = jnp.exp(scores - m_new[..., None])
            correction = jnp.exp(m - m_new)
            l_new = l * correction + p.sum(axis=-1)
            acc_new = acc * correction[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32,
            )
            return (acc_new, m_new, l_new), None

        acc0 = jnp.zeros((b, h, block_q, d), jnp.float32)
        m0 = jnp.full((b, h, block_q), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, block_q), jnp.float32)
        ks = jnp.arange(nk)
        (acc, m, l), _ = jax.lax.scan(
            kv_step,
            (acc0, m0, l0),
            (ks, kb.transpose(2, 0, 1, 3, 4), vb.transpose(2, 0, 1, 3, 4)),
        )
        # All-masked rows (can't happen for causal self-attention, where each
        # query sees at least itself) would give l=0; guard anyway.
        return acc / jnp.maximum(l, 1e-30)[..., None]

    out = jax.vmap(per_q_block, in_axes=(0, 2), out_axes=2)(
        jnp.arange(nq), qb
    )  # [B, H, nq, bq, D]
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)
