"""Selective activation checkpointing policies.

The reference wraps every transformer block in
``torch.utils.checkpoint.checkpoint`` with a *selective* policy that saves the
outputs of compute-intensive aten ops (mm/bmm/addmm/SDPA variants — reference
model/pytorch_utils.py:5-17, my_gpt2.py:145,175-183) and recomputes everything
else (layernorm/gelu/dropout) in backward.

The TPU-native equivalent is ``jax.checkpoint`` (remat) with
``checkpoint_dots``: save dot_general results, recompute elementwise ops —
the same "keep the MXU work, redo the VPU work" trade.
"""

from __future__ import annotations

import jax

_POLICIES = {
    # Save nothing: recompute the whole block in backward.
    "full": None,
    # Save matmul/attention outputs only — the analogue of the reference's
    # compute_intensive_ops list.
    "dots": jax.checkpoint_policies.checkpoint_dots,
    # Save matmuls except those with no batch dims (slightly leaner HBM).
    "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
}


def apply_remat(fn, mode: str, *, prevent_cse: bool = False, static_argnums=()):
    """Wrap ``fn`` in jax.checkpoint according to ``mode``.

    mode: "none" (identity), "full", "dots", "dots_no_batch".
    prevent_cse=False is safe (and faster) under scan-over-layers.
    """
    if mode == "none":
        return fn
    if mode not in _POLICIES:
        raise KeyError(f"unknown remat mode {mode!r}; known: none, {sorted(_POLICIES)}")
    policy = _POLICIES[mode]
    kwargs = dict(prevent_cse=prevent_cse, static_argnums=static_argnums)
    if policy is not None:
        kwargs["policy"] = policy
    return jax.checkpoint(fn, **kwargs)
