"""Selective activation checkpointing policies.

The reference wraps every transformer block in
``torch.utils.checkpoint.checkpoint`` with a *selective* policy that saves the
outputs of compute-intensive aten ops (mm/bmm/addmm/SDPA variants — reference
model/pytorch_utils.py:5-17, my_gpt2.py:145,175-183) and recomputes everything
else (layernorm/gelu/dropout) in backward.

The TPU-native equivalent is ``jax.checkpoint`` (remat) with
``checkpoint_dots``: save dot_general results, recompute elementwise ops —
the same "keep the MXU work, redo the VPU work" trade.
"""

from __future__ import annotations

import jax
from jax.ad_checkpoint import checkpoint_name  # noqa: F401  (models tag with this)

# Activation names the "names" policy saves — every projection/matmul output
# in a transformer block (models/gpt2.py and models/llama.py tag these with
# ``checkpoint_name``). This is the faithful analogue of the reference's
# compute_intensive_ops list: keep the MXU outputs, recompute VPU work.
#
# Crucially, UNLIKE ``checkpoint_dots`` it does NOT save the [B, H, T, T]
# attention score matmul (a "dot" too!): with naive attention at T=1024 that
# policy stores ~400 MB of f32 scores per layer — measured as ~33 ms/step of
# pure dynamic-update-slice HBM traffic on GPT-2 124M — while recomputing
# scores from the saved qkv in backward costs one extra small matmul.
SAVED_ACTIVATION_NAMES = (
    "qkv",        # gpt2 merged projection [B, T, 3E]
    "q", "k", "v",  # llama separate projections
    "attn_out",   # attention output [B, T, H, D] (the SDPA-save analogue)
    "attn_proj",  # output projection [B, T, E] (recomputes the ln_2 input)
    "mlp_fc",     # up projection
    "mlp_gate", "mlp_up",  # llama SwiGLU branches
    # NOT saved: "mlp_proj" (the down projection). Its value feeds only the
    # residual add whose output is the next layer's scan carry — already
    # saved — so storing it is pure HBM waste (measured ~4 ms/step).
)

def _contains_pallas_call(jaxpr, depth: int = 0) -> bool:
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # unwrap ClosedJaxpr
    if not hasattr(jaxpr, "eqns") or depth > 2:
        return False
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            return True
        for v in eqn.params.values():
            if hasattr(getattr(v, "jaxpr", v), "eqns") and _contains_pallas_call(
                v, depth + 1
            ):
                return True
    return False


def _flash_call_policy(prim, *_args, **params) -> bool:
    """Save all outputs of the Pallas flash-attention custom_vjp call —
    (o, l, m), see ops/pallas_flash._pallas_flash_olm. With those saved (and
    q/k/v derivable from the saved qkv projection) the backward pass skips
    the forward kernel re-run entirely. Identified structurally: the only
    custom_vjp whose body is a pallas_call inside our models is flash."""
    if prim.name != "custom_vjp_call":
        return False
    return _contains_pallas_call(params.get("call_jaxpr"))


_POLICIES = {
    # Save nothing: recompute the whole block in backward.
    "full": None,
    # Save matmul/attention outputs only — the analogue of the reference's
    # compute_intensive_ops list.
    "dots": jax.checkpoint_policies.checkpoint_dots,
    # Save matmuls except those with no batch dims (slightly leaner HBM).
    "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    # Save exactly the tagged projection outputs (recommended: avoids saving
    # the quadratic attention-score dot that "dots" keeps) plus the flash
    # kernel's (o, l, m) so backward launches only the dq/dkv kernels.
    "names": jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.save_only_these_names(
            *SAVED_ACTIVATION_NAMES
        ),
        _flash_call_policy,
    ),
    # Save ONLY the flash kernel's (o, l, m): removes the O(T^2)
    # forward-kernel re-run from backward while keeping every linear-in-T
    # projection save OFF — the long-context policy for regimes where the
    # per-layer gate/up saves are what OOM HBM (llama3-1B T=8192 fits
    # with this or "full"; "names"/"dots" exceed the chip — measured
    # round 5, benchmarks/PERF_NOTES.md).
    "flash": _flash_call_policy,
}


def apply_remat(fn, mode: str, *, prevent_cse: bool = False, static_argnums=()):
    """Wrap ``fn`` in jax.checkpoint according to ``mode``.

    mode: "none" (identity), "full", "dots", "dots_no_batch", "names",
    "flash". prevent_cse=False is safe (and faster) under
    scan-over-layers.
    """
    if mode == "none":
        return fn
    if mode not in _POLICIES:
        raise KeyError(f"unknown remat mode {mode!r}; known: none, {sorted(_POLICIES)}")
    policy = _POLICIES[mode]
    kwargs = dict(prevent_cse=prevent_cse, static_argnums=static_argnums)
    if policy is not None:
        kwargs["policy"] = policy
    return jax.checkpoint(fn, **kwargs)
