"""int8 quantization for the bandwidth-bound serving path.

Decode streams the whole KV pool and the full weight set through HBM for
every token; quantizing both to int8 roughly quarters the bytes against
the f32 rig config (halves them against a bf16 deployment) on the two
largest traffic terms. Two quantization families live here, each shaped
by where its bytes sit:

1. **KV pages** (``quantize_kv`` / ``dequantize_kv``): symmetric int8
   with a PER-TOKEN, PER-KV-HEAD f32 scale (``scale[b, t, h] =
   max|x[b, t, h, :]| / 127``), stored page-aligned next to the value
   pages (``[L, P, page, Hkv]`` scale leaves beside the
   ``[L, P, page, Hkv, D]`` int8 leaves — serving/block_pool.py's pool
   layout). Per-token granularity is NOT a tuning choice, it is the
   soundness condition of the paged cache: pages fill incrementally
   (append on decode, chunk-at-a-time on prefill), so a scale shared
   across a page would be re-derived every append and silently
   re-quantize — i.e. corrupt — the positions already written. A
   per-token scale depends only on that token's K/V, which also makes
   quantization DETERMINISTIC per position: a fault-resume re-prefill
   reproduces bit-identical pages, so the PR-6/PR-8 token-identical
   recovery contracts survive quantization verbatim
   (tests/test_serving_quant.py re-pins them).

2. **Weights** (``quantize_weight`` / ``quantize_decode_params``):
   weight-only int8 with a PER-OUTPUT-CHANNEL f32 scale over the
   contracting dim, applied to the block projection matmuls of the
   decode path (QKV/out projections + MLP). ``qdot``/``ops.layers.dense``
   compute ``(x @ q8.astype(x.dtype)) * scale`` — the int8 kernel is
   upcast in-register ahead of the MXU, so HBM traffic is the int8
   bytes while accumulation stays in the activation dtype. The scale is
   a linear factor applied BEFORE any tensor-parallel psum, so
   row-parallel projections reduce scaled partials and the TP
   collective structure (pinned all-reduce counts) is untouched.
   Embeddings, the LM head, and norms stay full precision: they are a
   small fraction of decode bytes and the head feeds the sampler
   directly, where quantization noise buys nothing.

Quality is CONTRACTUAL, not anecdotal: ``relative_logit_mse`` and
``token_match_rate`` are the two pinned metrics (``Q8_QUALITY`` carries
the budgets the tests and ``decode_bench --kv-quant int8`` assert), and
the dtype-leak audit grows a q8 cast budget
(analysis/audit.check_q8_casts) so a silent f32 round-trip — an extra
quantize or dequantize beyond the declared sites — fails the audit
instead of just burning bandwidth.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Pinned quality budgets for the int8 serving path, asserted by
# tests/test_serving_quant.py and scripts/decode_bench.py --kv-quant
# int8 (the CI smoke FAILS on breach — the budget is a contract the way
# the bit-equivalence pins are, not a printed observation).
#
# The pinned token metric is TEACHER-FORCED greedy agreement
# (``argmax_agreement`` over both engines' logits for IDENTICAL
# contexts): it measures quantization error and nothing else. The
# autoregressive prefix-match rate (``token_match_rate`` over engine
# outputs) is reported alongside but NOT pinned — on a random-init
# bench model a ~2%-per-step argmax flip compounds geometrically over a
# 32-token generation (0.98^32 ~ 0.52), so the prefix metric mostly
# measures how chaotic an uncalibrated model's near-ties are, not how
# lossy int8 is; a trained model's logit gaps make it far tamer.
#
# Measured headroom on the bench config (vocab 2048, 8 layers):
# relative logit MSE ~1e-5 (kv-only) / ~4e-4 (kv+weights);
# teacher-forced agreement 0.992 (kv-only) / 0.956 (kv+weights). The
# pins leave margin for config drift without letting a real regression
# through — a lost scale or a per-page rescale moves these metrics by
# orders of magnitude, not percents.
Q8_QUALITY = {
    "max_relative_logit_mse": 2e-3,
    "min_token_match_rate": 0.90,
}

_EPS = 1e-30


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 with a per-token, per-head scale: ``x`` is
    ``[..., D]`` (typically [B, T, Hkv, D] new K or V), the scale is
    computed over the trailing head_dim only. Returns (int8 values of
    x.shape, f32 scales of x.shape[:-1]). All-zero rows get scale 1 so
    dequantization reproduces exact zeros (no 0/0); values round to
    nearest and clamp to [-127, 127] (the symmetric range — -128 is
    never emitted, so |dequant| <= amax always)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(x32 / jnp.maximum(scale, _EPS)[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Inverse of ``quantize_kv``: ``q`` [..., D] int8, ``scale``
    [...] f32 -> values in ``dtype``."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


# -- weight-only int8 -------------------------------------------------------

# A quantized weight is a plain dict pytree so it rides shard_map specs,
# device_put trees, and scan-over-layers slicing with zero machinery.
_QKEYS = frozenset({"q8", "scale"})


def is_quantized(w) -> bool:
    return isinstance(w, dict) and set(w) == _QKEYS


def quantize_weight(w: jax.Array, contract_axis: int = 0) -> dict:
    """Per-output-channel symmetric int8: the scale reduces over
    ``contract_axis`` (the matmul's contracting dim), one f32 scale per
    remaining (output) coordinate. Stacked block leaves [L, in, out...]
    pass ``contract_axis=1`` so each layer quantizes independently."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=contract_axis)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(w32 / jnp.expand_dims(jnp.maximum(scale, _EPS),
                                        contract_axis))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return {"q8": q, "scale": scale.astype(jnp.float32)}


def qdot(x: jax.Array, w, *, precision=None) -> jax.Array:
    """``x @ w`` where ``w`` is a plain [in, out...] array (bit-identical
    to the pre-quant ``x @ w.astype(x.dtype)``) or a ``quantize_weight``
    dict (int8 kernel upcast in-register, per-channel scale applied to
    the output — weight-only quantization, accumulation in x.dtype).
    THE one definition of the quantized matmul: ``ops.layers.dense``
    delegates here, so the gpt2 (dense) and llama (raw-matmul) decode
    paths can never diverge on the quantization contract."""
    if is_quantized(w):
        y = jax.lax.dot_general(
            x, w["q8"].astype(x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
            precision=precision,
        )
        return y * w["scale"].astype(y.dtype)
    return jax.lax.dot_general(
        x, w.astype(x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
        precision=precision,
    )


# The decode-path projection weights quantize_decode_params targets,
# keyed by param-path suffix exactly like parallel/sharding._TENSOR_RULES
# (dense-family blocks only — embeddings/head/norms stay full precision,
# MoE expert stacks are rejected at the engine). The stacked [L, in,
# out...] leaves contract dim 1, hence contract_axis=1 below.
QUANT_WEIGHT_SUFFIXES: frozenset[tuple[str, ...]] = frozenset({
    ("attn", "c_attn", "kernel"),
    ("attn", "c_proj", "kernel"),
    ("mlp", "c_fc", "kernel"),
    ("mlp", "c_proj", "kernel"),
    ("attn", "wq"),
    ("attn", "wk"),
    ("attn", "wv"),
    ("attn", "wo"),
    ("mlp", "gate"),
    ("mlp", "up"),
    ("mlp", "down"),
})
_SUFFIX_LENS = (3, 2)


def _path_keys(path) -> tuple[str, ...]:
    return tuple(
        getattr(p, "key", None) if isinstance(getattr(p, "key", None), str)
        else str(p)
        for p in path
    )


def _is_quant_path(path) -> bool:
    keys = _path_keys(path)
    if not keys or keys[0] != "blocks":
        return False
    return any(
        len(keys) >= n and keys[-n:] in QUANT_WEIGHT_SUFFIXES
        for n in _SUFFIX_LENS
    )


def quantize_decode_params(params):
    """Quantize the block projection weights of a decode params tree
    (int8 kernel + per-out-channel scale per QUANT_WEIGHT_SUFFIXES);
    everything else — embeddings, head, norms, biases — passes through
    untouched. Pure function of the weights: engines call it ONCE per
    params tree (identity-memoized) at first dispatch."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            quantize_weight(leaf, contract_axis=1)
            if _is_quant_path(path)
            else leaf
        ),
        params,
    )


def quantized_param_specs(p_specs, params_abstract):
    """Map an (unquantized) PartitionSpec tree to the quantized params
    tree's structure: a quantized kernel keeps its spec on ``q8`` and
    drops the contracting dim's entry (stacked leaves: index 1) for
    ``scale`` — column-parallel scales shard with their output channels,
    row-parallel scales replicate, exactly matching the local outputs
    ``qdot`` multiplies them into under shard_map."""
    from jax.sharding import PartitionSpec as P

    def map_leaf(path, spec, leaf):
        if not _is_quant_path(path):
            return spec
        entries = list(tuple(spec)) + [None] * (leaf.ndim - len(tuple(spec)))
        del entries[1]  # the stacked leaf's contracting (in) dim
        scale_spec = P(*entries) if any(e for e in entries) else P()
        return {"q8": spec, "scale": scale_spec}

    return jax.tree_util.tree_map_with_path(
        map_leaf, p_specs, params_abstract,
        is_leaf=lambda x: isinstance(x, P),
    )


# -- quality metrics --------------------------------------------------------


def relative_logit_mse(ref_logits, q_logits) -> float:
    """Scale-free logit error: mean((q - ref)^2) / mean(ref^2) — the
    pinnable form (absolute MSE moves with model width/init scale, the
    ratio does not)."""
    ref = np.asarray(ref_logits, np.float64)
    q = np.asarray(q_logits, np.float64)
    denom = max(float(np.mean(ref * ref)), _EPS)
    return float(np.mean((q - ref) ** 2) / denom)


def argmax_agreement(ref_logits, q_logits) -> float:
    """Teacher-forced greedy agreement: the fraction of positions where
    both logit tensors ([..., V], IDENTICAL input contexts) pick the
    same argmax — the PINNED token metric (see Q8_QUALITY: measures
    quantization error without autoregressive compounding)."""
    ref = np.argmax(np.asarray(ref_logits), axis=-1)
    q = np.argmax(np.asarray(q_logits), axis=-1)
    return float(np.mean(ref == q))


def token_match_rate(ref_tokens, q_tokens) -> float:
    """Greedy-continuation agreement over paired token sequences:
    sum(longest common PREFIX) / sum(len) — prefix-based because the
    first divergent token changes the context of everything after it
    (positions past the split are different inputs, not comparable
    errors). 1.0 = every sequence identical."""
    total = matched = 0
    for r, q in zip(ref_tokens, q_tokens, strict=True):
        r = np.asarray(r)
        q = np.asarray(q)
        n = min(r.shape[0], q.shape[0])
        agree = r[:n] == q[:n]
        m = int(agree.argmin()) if not agree.all() else n
        matched += m
        total += max(r.shape[0], q.shape[0])
    return matched / max(total, 1)
