"""Megatron-style tensor-parallel conjugate operators (the f / g pair).

For hand-written (shard_map) tensor parallelism the model needs exactly two
communication-bearing ops (Megatron-LM §3: the ``f`` and ``g`` conjugates):

- ``tp_copy`` (f): identity in forward — the activation entering a
  column-parallel region is used by EVERY tensor shard — and ``psum`` over
  the tensor axis in backward, because each shard's cotangent covers only
  its own heads/columns. Placed between a norm and the column-parallel
  matmul so the norm's (replicated) param grads come out exact on every
  shard with no post-hoc reduction.
- ``tp_reduce`` (g): ``psum`` in forward — row-parallel matmuls produce
  partial sums over the sharded contraction dim — and identity in backward
  (the reduced activation's cotangent is already full on every shard).

Biases of row-parallel projections must be added AFTER ``tp_reduce`` (they
are replicated; adding before the psum would count them tensor-ways).

Both ops are no-ops when ``axis`` is None, so model code can thread an
optional ``tensor_axis`` straight through. Under shard_map's varying-axes
typing, ``tp_reduce`` output is invariant over the tensor axis (psum), which
is exactly the "activations replicated between parallel regions" contract.
"""

from __future__ import annotations

import functools

import jax

from pytorch_distributed_tpu.utils.compat import pcast_varying, vma_of


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_copy(x, axis):
    # Value-identity, but TYPED varying over the tensor axis: downstream
    # per-shard compute then carries varying cotangents and the ONLY psum is
    # the hand-written one in the backward rule below. (If the output stayed
    # typed invariant, vma-aware AD would insert its own psum when
    # transposing the first sharded-matmul use — double-counting with ours.)
    return pcast_varying(x, (axis,))


def _tp_copy_fwd(x, axis):
    return _tp_copy(x, axis), None


def _tp_copy_bwd(axis, _res, g):
    return (jax.lax.psum(g, axis),)


_tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_reduce(x, axis):
    return jax.lax.psum(x, axis)


def _tp_reduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _tp_reduce_bwd(axis, _res, g):
    return (g,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


def tp_copy(x: jax.Array, axis: str | None) -> jax.Array:
    """Identity fwd / psum-over-axis bwd (Megatron f). No-op if axis None."""
    return x if axis is None else _tp_copy(x, axis)


def pvary_missing(x: jax.Array, axes) -> jax.Array:
    """pcast ``x`` to varying on whichever of ``axes`` it is not already
    varying on (pcast rejects axes that are already varying). The shared
    helper for initialising shard_map scan/cond accumulators under
    check_vma typing."""
    have = vma_of(x)
    need = tuple(ax for ax in axes if ax not in have)
    return pcast_varying(x, need)


def tp_reduce(x: jax.Array, axis: str | None) -> jax.Array:
    """psum-over-axis fwd / identity bwd (Megatron g). No-op if axis None."""
    return x if axis is None else _tp_reduce(x, axis)
