"""Ring attention: sequence-parallel causal attention over a mesh axis.

Long-context scaling the reference cannot do at all (SURVEY.md §5.7: the
reference materialises the full T×T score matrix, reference my_gpt2.py:63-77,
and is hard-capped at n_ctx by its precomputed mask buffer, :29-36). Here the
sequence dimension is sharded over a mesh axis: each device holds a
[B, T/N, H, D] slice of Q/K/V, and K/V blocks rotate around the ring via
``lax.ppermute`` while each device accumulates its queries' attention with a
flash-style online softmax. Peak memory per device is O(T/N · T/N) for one
score block instead of O(T²); ICI neighbour-exchange bandwidth overlaps with
the per-block matmuls.

Math (standard blockwise softmax accumulation): per incoming KV block
  s   = q·kᵀ/√d  (masked)
  m'  = max(m, rowmax(s))
  p   = exp(s - m')
  o   = o·exp(m-m') + p·v
  l   = l·exp(m-m') + rowsum(p)
and ``out = o / l`` after the ring completes. The self block is processed
first (step 0), so ``m`` is finite from the first accumulation — every causal
query row attends at least to itself. Fully-masked future blocks (source
shard > own shard) skip their matmuls via ``lax.cond``; the ring still pays
all n exchanges and the last shard does the most useful work (n blocks vs 1
for shard 0) — inherent to contiguous-block causal CP.

Must be called inside ``shard_map`` with ``axis_name`` bound and the sequence
dim of q/k/v sharded over that axis. Differentiable end-to-end: the ring is a
``lax.scan`` and AD transposes each ``ppermute`` into the reverse rotation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import NEG_INF, _repeat_kv
from pytorch_distributed_tpu.utils.compat import vma_of


def ring_attention(
    q: jax.Array,  # [B, Tl, H, D] — local query shard
    k: jax.Array,  # [B, Tl, Hkv, D]
    v: jax.Array,  # [B, Tl, Hkv, D]
    *,
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """Returns the local output shard [B, Tl, H, D].

    Global semantics are identical to ``naive_attention`` on the unsharded
    [B, T, H, D] arrays (tested vs. it in tests/test_ring_attention.py).
    Softmax statistics are kept in float32 regardless of input dtype.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    n_rep = h // k.shape[2]

    scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))
    # Send each device's KV block to the NEXT device: after s steps, device
    # idx holds the block that started on device (idx - s) mod n.
    perm = [(i, (i + 1) % n) for i in range(n)]
    qpos = idx * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)

    def accumulate(acc, kb, vb, step):
        """Fold one KV block into the running (o, m, l) softmax state."""
        o, m, l = acc
        src = (idx - step) % n
        # GQA heads are expanded here, AFTER the ring exchange, so the
        # neighbour traffic moves the unexpanded [B, Tl, Hkv, D] blocks.
        kb = _repeat_kv(kb, n_rep)
        vb = _repeat_kv(vb, n_rep)

        # [B, H, Tl, Tl] block scores in f32 (one MXU matmul per block).
        s = (
            jnp.einsum("bthd,bshd->bhts", q, kb,
                       preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            kpos = src * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # Fully-masked blocks (src > idx) leave m unchanged; p underflows to 0
        # because m is already finite after the step-0 self block.
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        o = o * alpha[..., None] + jnp.einsum(
            "bhts,bshd->bhtd", p, vb.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        l = l * alpha + jnp.sum(p, axis=-1)
        return o, m_new, l

    def ring_step(carry, step):
        kb, vb, acc = carry
        if causal:
            # Blocks from later shards (src > idx) are fully masked — skip
            # their matmuls entirely. (The ring still pays n exchanges and is
            # load-imbalanced: device idx does idx+1 useful blocks. A
            # striped/zigzag token layout would balance it at the cost of a
            # permuted data contract; not worth it at parity scale.)
            src = (idx - step) % n
            acc = jax.lax.cond(
                src <= idx,
                lambda a: accumulate(a, kb, vb, step),
                lambda a: a,
                acc,
            )
        else:
            acc = accumulate(acc, kb, vb, step)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (kb, vb, acc), None

    # Accumulators hold device-varying values; mark them so under shard_map's
    # varying-manual-axes typing (constants start out unvarying). They must
    # vary on every axis the INPUTS vary on (not just the ring axis — the
    # batch dim is typically sharded over data/fsdp axes too), or the
    # lax.cond/scan branches disagree on types.
    target_vma = frozenset().union(
        *(vma_of(a) for a in (q, k, v))
    ) | {axis_name}

    def varying(x):
        from pytorch_distributed_tpu.ops.tp import pvary_missing

        return pvary_missing(x, tuple(target_vma))

    acc0 = (
        varying(jnp.zeros((b, h, tl, d), jnp.float32)),
        varying(jnp.full((b, h, tl), NEG_INF, jnp.float32)),
        varying(jnp.zeros((b, h, tl), jnp.float32)),
    )
    # n-1 exchange steps in the scan; the final block needs no ppermute.
    (kb, vb, acc), _ = jax.lax.scan(
        ring_step, (k, v, acc0), jnp.arange(n - 1)
    )
    if causal:
        # Same skip as in ring_step: the final block (src = (idx+1) mod n)
        # is fully masked for every shard except idx = n-1 — without the
        # guard, n-1 of n devices pay its QK^T and PV matmuls for a zero
        # contribution.
        src = (idx - (n - 1)) % n
        o, m, l = jax.lax.cond(
            src <= idx,
            lambda a: accumulate(a, kb, vb, n - 1),
            lambda a: a,
            acc,
        )
    else:
        o, m, l = accumulate(acc, kb, vb, n - 1)

    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(v.dtype)
