"""Scan-over-layers with an optional latency-hiding prefetch window.

Both model families run their transformer stack as one ``lax.scan`` over
stacked [L, ...] block params, with an optional ``block_transform``
(explicit FSDP's just-in-time per-layer all_gather) applied inside the
rematted body. That just-in-time schedule serialises on a real
interconnect: the scan body is

    gather(l) -> block(l) -> gather(l+1) -> block(l+1) -> ...

with every gather on the critical path (XLA cannot overlap a collective
across a while-loop iteration boundary, so the MXU idles for each one —
the exact stall SimpleFSDP (arXiv:2411.00284) removes by
bucketing + reordering).

``scan_layers`` here factors the scan out of the models and adds a
**windowed double-buffer schedule**: with window W = prefetch_buffers + 1
the scan runs over L/W windows, and each window's (rematted) body issues
ALL W layer gathers before the first block computes:

    gather(l) ; gather(l+1) ; ... ; gather(l+W-1)   # no deps between them
    block(l) -> block(l+1) -> ... -> block(l+W-1)

Only gather(l) is on the critical path — gather(l+j) has no data
dependence on block(l..l+j-1), so XLA's latency-hiding scheduler lowers
it to an ``all-gather-start`` at the window top with the ``-done`` just
before block(l+j): layer l+1's params stream in while layer l computes.
Because the transform runs INSIDE the rematted window body, backward
replays the window: it re-gathers all W layers up front (the same
prefetch, mirrored) and the AD-transposed ``psum_scatter``s of the
window's grads interleave with the remaining backward compute instead of
each stalling its own layer. Residuals stay the sharded xs slices + the
per-window carry — gathered params are never saved, preserving ZeRO-3's
memory contract (the live-buffer cost is exactly W gathered layers).

Numerics: each layer sees byte-identical inputs in the identical order
(the window only reshapes the stacked leaves and hoists independent
collectives), so the schedule is bit-equivalent to the W=1 scan — pinned
by tests/test_prefetch.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.remat import apply_remat


def effective_window(prefetch_buffers: int, n_layer: int) -> int:
    """Largest divisor of ``n_layer`` that is <= prefetch_buffers + 1.

    ``prefetch_buffers`` is a SOFT size: windows must tile the layer
    stack exactly (a ragged tail window would compile a second block
    body), so the request is rounded down to the nearest divisor — 1
    (no prefetch) in the worst case, n_layer (one window spanning the
    whole stack) at most."""
    if prefetch_buffers <= 0 or n_layer <= 1:
        return 1
    want = min(prefetch_buffers + 1, n_layer)
    for w in range(want, 0, -1):
        if n_layer % w == 0:
            return w
    return 1


def scan_layers(
    block_fn: Callable,
    carry,
    blocks,
    extras=None,
    *,
    remat_mode: str,
    block_transform: Callable | None = None,
    prefetch_buffers: int = 0,
    unroll: int = 1,
    collect_ys: bool = False,
):
    """Run ``block_fn`` over every layer of a stacked [L, ...] param tree.

    ``block_fn(carry, bp, extra) -> carry`` consumes one layer's
    (already-transformed) params plus its slice of ``extras`` (e.g. the
    layer index driving per-layer dropout keys; pass None when unused).
    ``block_transform`` maps each layer's sliced subtree before use (the
    explicit-FSDP gather hook); with ``prefetch_buffers`` > 0 the
    transforms of a whole window are hoisted above its compute (see
    module docstring). Returns the final carry.

    ``collect_ys``: when True, ``block_fn`` returns ``(carry, y)`` and the
    per-layer ys are stacked back to [L, ...] and returned alongside the
    carry — the decode path's per-layer KV-cache updates ride this the
    same way training's scan outputs would, so the windowed prefetch
    schedule applies to inference too (serving/engine.py's ZeRO-3 decode).
    In window mode the per-window ys are stacked [W, ...] inside the body
    and reshaped [n_windows, W, ...] -> [L, ...] afterwards — the same
    layer order as the W=1 scan, so ys stay bit-identical across window
    sizes.
    """
    n_layer = jax.tree.leaves(blocks)[0].shape[0]
    window = effective_window(prefetch_buffers, n_layer)

    def transform(bp):
        return block_transform(bp) if block_transform is not None else bp

    if window <= 1:
        # The classic per-layer scan (bit-identical to the pre-refactor
        # model code): transform + compute inside one rematted body.
        def body(c, xs):
            bp, extra = xs
            if collect_ys:
                return block_fn(c, transform(bp), extra)
            return block_fn(c, transform(bp), extra), None

        (carry, ys) = jax.lax.scan(
            apply_remat(body, remat_mode),
            carry,
            (blocks, extras),
            unroll=unroll,
        )
        return (carry, ys) if collect_ys else carry

    n_windows = n_layer // window
    blocks_w = jax.tree.map(
        lambda a: a.reshape((n_windows, window) + a.shape[1:]), blocks
    )
    extras_w = jax.tree.map(
        lambda a: a.reshape((n_windows, window) + a.shape[1:]), extras
    )

    def window_body(c, xs):
        bw, ew = xs
        # Prefetch: every gather in the window is issued before any
        # block computes. The loop is unrolled at trace time (window is
        # static), so these are W independent collectives in one body.
        gathered = [
            transform(jax.tree.map(lambda a, j=j: a[j], bw))
            for j in range(window)
        ]
        ys_w = []
        for j in range(window):
            out = block_fn(
                c, gathered[j], jax.tree.map(lambda a, j=j: a[j], ew)
            )
            if collect_ys:
                c, y = out
                ys_w.append(y)
            else:
                c = out
        if collect_ys:
            return c, jax.tree.map(lambda *zs: jnp.stack(zs), *ys_w)
        return c, None

    (carry, ys) = jax.lax.scan(
        apply_remat(window_body, remat_mode),
        carry,
        (blocks_w, extras_w),
        unroll=unroll,
    )
    if collect_ys:
        ys = jax.tree.map(
            lambda a: a.reshape((n_layer,) + a.shape[2:]), ys
        )
        return carry, ys
    return carry
