"""Mixture-of-Experts MLP with expert parallelism (Switch-style top-1).

Beyond-reference capability (the reference MLP is dense, my_gpt2.py:80-99):
the block's MLP is replaced by n_experts expert MLPs and a learned top-1
router, in the Mesh-TensorFlow/Switch formulation:

  router logits [T, X] -> top-1 expert per token; position-in-expert by
  cumsum; tokens beyond the per-expert capacity C are dropped (their MLP
  output is zero — the residual stream carries them unchanged).
  dispatch one-hot [T, X, C] scatters token vectors to [X, C, D] expert
  batches; experts run as ONE batched matmul pair (MXU-friendly — no
  ragged shapes, no host control flow); combine weights (the router
  probability at the kept position) gather outputs back to [T, D].

Expert parallelism (``expert_axis`` inside shard_map): expert weights are
sharded over the axis, tokens are sharded over it too (it acts as a data
axis for non-expert parameters), and two ``all_to_all`` collectives move
token slots to their expert's owner and back:

  [X, C_local, D] --all_to_all--> [X/n, n*C_local, D]   (dispatch)
  expert compute on local experts
  [X/n, n*C_local, D] --all_to_all--> [X, C_local, D]   (return)

Capacity semantics under EP are per-shard (each shard may send up to
C_local tokens to each expert), so a generous capacity_factor reproduces
the single-device result exactly — pinned by tests/test_moe.py.

Deterministic routing (no jitter noise). The Switch load-balancing
auxiliary loss is returned alongside the output and both trainer paths add
``moe_aux_coef * aux`` to the objective; under EP it is computed per
token-shard and averaged (the standard distributed convention — differs
from the global-batch product only at O(1e-4) on balanced batches).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def expert_capacity(
    tokens: int, n_experts: int, capacity_factor: float
) -> int:
    """Per-expert token slots: ceil(tokens/experts * factor), min 1."""
    return max(1, int(tokens * capacity_factor / n_experts + 0.999999))


def moe_mlp(
    x: jax.Array,  # [B, T, D]
    params: dict,  # router [D, X]; w_in [X, D, F]; w_out [X, F, D];
    #               optional w_gate [X, D, F] (SwiGLU experts)
    *,
    activation,
    capacity_factor: float = 1.25,
    expert_axis: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Returns (output [B, T, D], aux_loss scalar).

    aux_loss is the Switch load-balancing term: X * sum_e(fraction_e *
    mean_prob_e), minimised (=1) by uniform routing.
    """
    b, t, d = x.shape
    n_tokens = b * t
    xt = x.reshape(n_tokens, d)
    n_experts = params["router"].shape[-1]

    # --- routing (f32 for a stable softmax) ------------------------------
    logits = (
        xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    )  # [T, X]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    one_hot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)
    # Position of each token within its expert's queue (0-based).
    pos_in_expert = (jnp.cumsum(one_hot, axis=0) - one_hot) * one_hot
    pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32)  # [T]
    cap = expert_capacity(n_tokens, n_experts, capacity_factor)
    keep = pos < cap

    # Switch aux loss: fraction of tokens per expert x mean router prob.
    fraction = jnp.mean(one_hot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = n_experts * jnp.sum(fraction * mean_prob)

    # --- dispatch: [T, X, C] one-hot scatter -----------------------------
    dispatch = (
        one_hot * keep[:, None]
    )[:, :, None] * jax.nn.one_hot(pos, cap, dtype=jnp.float32)[:, None, :]
    expert_in = jnp.einsum(
        "txc,td->xcd", dispatch, xt.astype(jnp.float32)
    ).astype(x.dtype)  # [X, C, D]

    if expert_axis is not None:
        # Send each expert's slots to its owning shard; slots from all
        # shards concatenate along the capacity dim.
        expert_in = jax.lax.all_to_all(
            expert_in, expert_axis, split_axis=0, concat_axis=1, tiled=True
        )  # [X/n, n*C, D]

    # --- expert compute: batched matmuls ---------------------------------
    # Dense-style experts: act(x @ w_in) @ w_out (gpt2 family).
    # Gated (SwiGLU) experts, params include "w_gate":
    # (act(x @ w_gate) * (x @ w_in)) @ w_out (llama family; w_in is the
    # up-projection).
    h = jnp.einsum(
        "xcd,xdf->xcf", expert_in, params["w_in"].astype(expert_in.dtype)
    )
    if "w_gate" in params:
        g = jnp.einsum(
            "xcd,xdf->xcf", expert_in,
            params["w_gate"].astype(expert_in.dtype),
        )
        h = activation(g) * h
    else:
        h = activation(h)
    expert_out = jnp.einsum(
        "xcf,xfd->xcd", h, params["w_out"].astype(h.dtype)
    )

    if expert_axis is not None:
        expert_out = jax.lax.all_to_all(
            expert_out, expert_axis, split_axis=1, concat_axis=0, tiled=True
        )  # back to [X, C, D]

    # --- combine: gather each token's slot, scale by its gate ------------
    combine = dispatch * gate[:, None, None]
    out = jnp.einsum(
        "txc,xcd->td", combine, expert_out.astype(jnp.float32)
    )
    return out.astype(x.dtype).reshape(b, t, d), aux_loss
