"""Mixture-of-Experts MLP with expert parallelism (top-1 Switch / top-k).

Beyond-reference capability (the reference MLP is dense, my_gpt2.py:80-99):
the block's MLP is replaced by n_experts expert MLPs and a learned router.

Routing:
- ``top_k=1`` (default): Switch semantics — each token goes to its argmax
  expert, gated by that expert's router probability.
- ``top_k>1``: GShard-style — each token goes to its k highest-probability
  experts; the selected probabilities are renormalised to sum to 1.

Capacity: per-expert token slots C = ceil(T * factor / X); assignments past
capacity are dropped (their MLP contribution is zero — the residual stream
carries the token unchanged). Assignment priority is token order, then
choice rank — identical between both dispatch implementations below.

Two dispatch implementations behind ``dispatch_impl``:

- ``"einsum"`` — the Mesh-TensorFlow/Switch one-hot formulation: a
  [A, X, C] f32 dispatch tensor (A = T*top_k assignments) drives a pair of
  einsums. MXU-friendly and exactly differentiable, but the dispatch
  tensor is O(T·X·C) — the textbook-unscalable form (T=8192, X=64, C=160
  would be 3.4 GB per layer per microbatch).
- ``"sort"`` — scalable path: assignments are stably sorted by expert id,
  position-in-expert comes from a bincount/segment arithmetic, and tokens
  move through 1-D gathers/scatters into the SAME [X, C, D] expert-batch
  layout. Memory O(A·D + X·C·D); no [A, X, C] tensor ever exists. XLA
  sorts/gathers compile to fast TPU kernels, and the expert compute is the
  same pair of batched matmuls.
- ``"auto"`` picks einsum while the dispatch tensor stays small (exact
  parity path at test scale), sort beyond ``_AUTO_EINSUM_LIMIT`` elements.

Equivalence of the two is pinned by tests/test_moe.py (same routing, same
drops, same outputs within fp tolerance).

Expert parallelism (``expert_axis`` inside shard_map): expert weights are
sharded over the axis, tokens are sharded over it too (it acts as a data
axis for non-expert parameters), and two ``all_to_all`` collectives move
token slots to their expert's owner and back:

  [X, C_local, D] --all_to_all--> [X/n, n*C_local, D]   (dispatch)
  expert compute on local experts
  [X/n, n*C_local, D] --all_to_all--> [X, C_local, D]   (return)

Capacity semantics under EP are per-shard (each shard may send up to
C_local tokens to each expert), so a generous capacity_factor reproduces
the single-device result exactly — pinned by tests/test_moe.py.

Deterministic routing (no jitter noise). The Switch load-balancing
auxiliary loss (computed from FIRST-choice assignment fractions, which for
top_k=1 is exactly the Switch term) is returned alongside the output and
both trainer paths add ``moe_aux_coef * aux`` to the objective; under EP it
is computed per token-shard and averaged (the standard distributed
convention — differs from the global-batch product only at O(1e-4) on
balanced batches).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# "auto" switches einsum -> sort once the [A, X, C] dispatch tensor would
# exceed this many elements (64 MiB of f32).
_AUTO_EINSUM_LIMIT = 16 * 1024 * 1024


def expert_capacity(
    tokens: int, n_experts: int, capacity_factor: float
) -> int:
    """Per-expert token slots: ceil(tokens/experts * factor), min 1."""
    return max(1, int(tokens * capacity_factor / n_experts + 0.999999))


def _route(xt: jax.Array, router: jax.Array, top_k: int):
    """Router forward: returns (expert_idx [T,K], gates [T,K], probs [T,X]).

    f32 softmax for stability. top_k=1 keeps Switch gating (raw prob);
    top_k>1 renormalises the selected probs (GShard).
    """
    logits = xt.astype(jnp.float32) @ router.astype(jnp.float32)  # [T, X]
    probs = jax.nn.softmax(logits, axis=-1)
    if top_k == 1:
        idx = jnp.argmax(probs, axis=-1)[:, None]  # [T, 1]
        gates = jnp.take_along_axis(probs, idx, axis=-1)  # [T, 1]
    else:
        gates, idx = jax.lax.top_k(probs, top_k)  # [T, K]
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return idx, gates, probs


def _expert_compute(expert_in, params, activation, expert_axis,
                    tensor_axis=None):
    """[X, C, D] expert batches -> [X, C, D] outputs, with the EP
    all_to_all pair when expert_axis is set. Dense experts:
    act(x @ w_in) @ w_out; gated (SwiGLU) experts with "w_gate":
    (act(x @ w_gate) * (x @ w_in)) @ w_out.

    ``tensor_axis``: Megatron TP INSIDE each expert (EP x TP, the standard
    large-MoE placement): w_in/w_gate are column-parallel on their hidden
    dim F, w_out row-parallel on F, so each tensor shard computes its F/tp
    slice and ONE psum (tp_reduce) after w_out restores the full [X, C, D]
    output — the same f/g conjugate pair the dense blocks use (ops/tp.py).
    The router and dispatch run on replicated activations, so routing is
    identical across tensor shards."""
    if expert_axis is not None:
        # Send each expert's slots to its owning shard; slots from all
        # shards concatenate along the capacity dim.
        expert_in = jax.lax.all_to_all(
            expert_in, expert_axis, split_axis=0, concat_axis=1, tiled=True
        )  # [X/n, n*C, D]
    if tensor_axis is not None:
        from pytorch_distributed_tpu.ops.tp import tp_copy

        expert_in = tp_copy(expert_in, tensor_axis)
    h = jnp.einsum(
        "xcd,xdf->xcf", expert_in, params["w_in"].astype(expert_in.dtype)
    )
    if "w_gate" in params:
        g = jnp.einsum(
            "xcd,xdf->xcf", expert_in,
            params["w_gate"].astype(expert_in.dtype),
        )
        h = activation(g) * h
    else:
        h = activation(h)
    expert_out = jnp.einsum(
        "xcf,xfd->xcd", h, params["w_out"].astype(h.dtype)
    )
    if tensor_axis is not None:
        from pytorch_distributed_tpu.ops.tp import tp_reduce

        expert_out = tp_reduce(expert_out, tensor_axis)
    if expert_axis is not None:
        expert_out = jax.lax.all_to_all(
            expert_out, expert_axis, split_axis=1, concat_axis=0, tiled=True
        )  # back to [X, C, D]
    return expert_out


def _assignment_positions(e_flat: jax.Array, n_experts: int):
    """Position of each assignment within its expert's queue (0-based),
    priority = assignment order. Returns positions WITHOUT materialising
    a [A, X] cumsum when used by the sort path's caller.

    Sort-free formulation used by the einsum path would be the one-hot
    cumsum; here we compute it via stable sort + segment arithmetic so
    both paths share identical priority semantics."""
    a = e_flat.shape[0]
    order = jnp.argsort(e_flat, stable=True)  # assignment order preserved
    e_sorted = e_flat[order]
    counts = jnp.bincount(e_flat, length=n_experts)  # [X]
    starts = jnp.cumsum(counts) - counts  # exclusive cumsum
    pos_sorted = jnp.arange(a) - starts[e_sorted]
    # Scatter positions back to assignment order.
    pos = jnp.zeros((a,), jnp.int32).at[order].set(pos_sorted.astype(jnp.int32))
    return pos, order, e_sorted, pos_sorted


def _dispatch_einsum(
    xt, expert_idx, gates, n_experts, cap, params, activation, expert_axis,
    out_dtype, tensor_axis=None,
):
    """One-hot einsum dispatch (exact-parity / teaching path)."""
    t, k = expert_idx.shape
    a = t * k
    e_flat = expert_idx.reshape(a)
    pos, _, _, _ = _assignment_positions(e_flat, n_experts)
    keep = (pos < cap).astype(jnp.float32)

    onehot_e = jax.nn.one_hot(e_flat, n_experts, dtype=jnp.float32)
    onehot_c = jax.nn.one_hot(pos, cap, dtype=jnp.float32)
    # [A, X, C]: the textbook dispatch tensor.
    dispatch_a = (onehot_e * keep[:, None])[:, :, None] * onehot_c[:, None, :]
    dispatch = dispatch_a.reshape(t, k, n_experts, cap).sum(axis=1)
    combine = (
        dispatch_a * gates.reshape(a)[:, None, None]
    ).reshape(t, k, n_experts, cap).sum(axis=1)

    expert_in = jnp.einsum(
        "txc,td->xcd", dispatch, xt.astype(jnp.float32)
    ).astype(out_dtype)  # [X, C, D]
    expert_out = _expert_compute(
        expert_in, params, activation, expert_axis, tensor_axis
    )
    out = jnp.einsum("txc,xcd->td", combine, expert_out.astype(jnp.float32))
    return out


def _dispatch_sort(
    xt, expert_idx, gates, n_experts, cap, params, activation, expert_axis,
    out_dtype, tensor_axis=None,
):
    """Sort/segment dispatch: no [A, X, C] tensor, same semantics."""
    t, k = expert_idx.shape
    a = t * k
    d = xt.shape[-1]
    e_flat = expert_idx.reshape(a)
    tok_flat = jnp.repeat(jnp.arange(t), k)  # token of each assignment
    gate_flat = gates.reshape(a).astype(jnp.float32)

    _, order, e_sorted, pos_sorted = _assignment_positions(e_flat, n_experts)
    keep_sorted = pos_sorted < cap
    slot_sorted = e_sorted * cap + pos_sorted.astype(jnp.int32)  # [A]
    tok_sorted = tok_flat[order]
    gate_sorted = gate_flat[order]

    # Scatter kept assignments' token vectors into expert batches. Each
    # kept (expert, pos) pair is unique -> plain set; dropped assignments
    # get an out-of-range index and mode="drop" discards them.
    slot_or_oob = jnp.where(keep_sorted, slot_sorted, n_experts * cap)
    expert_in = (
        jnp.zeros((n_experts * cap, d), out_dtype)
        .at[slot_or_oob]
        .set(xt[tok_sorted].astype(out_dtype), mode="drop")
        .reshape(n_experts, cap, d)
    )

    expert_out = _expert_compute(
        expert_in, params, activation, expert_axis, tensor_axis
    )

    # Combine: each assignment gathers its slot's output, scaled by its
    # gate (0 for dropped), and segment-sums into its token.
    vals = expert_out.reshape(n_experts * cap, d).astype(jnp.float32)[
        jnp.minimum(slot_sorted, n_experts * cap - 1)
    ]
    weight = jnp.where(keep_sorted, gate_sorted, 0.0)[:, None]
    out = (
        jnp.zeros((t, d), jnp.float32).at[tok_sorted].add(vals * weight)
    )
    return out


def moe_mlp(
    x: jax.Array,  # [B, T, D]
    params: dict,  # router [D, X]; w_in [X, D, F]; w_out [X, F, D];
    #               optional w_gate [X, D, F] (SwiGLU experts)
    *,
    activation,
    capacity_factor: float = 1.25,
    expert_axis: str | None = None,
    tensor_axis: str | None = None,
    top_k: int = 1,
    dispatch_impl: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """Returns (output [B, T, D], aux_loss scalar).

    aux_loss is the Switch load-balancing term: X * sum_e(fraction_e *
    mean_prob_e) over FIRST-choice assignments, minimised (=1) by uniform
    routing.
    """
    b, t, d = x.shape
    n_tokens = b * t
    xt = x.reshape(n_tokens, d)
    n_experts = params["router"].shape[-1]
    if not (1 <= top_k <= n_experts):
        raise ValueError(f"top_k={top_k} out of range for {n_experts} experts")

    expert_idx, gates, probs = _route(xt, params["router"], top_k)

    # Capacity scales with the ASSIGNMENT count (GShard/t5x convention):
    # top-k routing produces k*T assignments, so per-expert slots must be
    # ceil(k*T*cf/X) or a perfectly balanced top-2 router would drop ~40%
    # of second choices at the default capacity factor.
    cap = expert_capacity(n_tokens * top_k, n_experts, capacity_factor)

    # Switch aux loss on first choices.
    first_onehot = jax.nn.one_hot(
        expert_idx[:, 0], n_experts, dtype=jnp.float32
    )
    fraction = jnp.mean(first_onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = n_experts * jnp.sum(fraction * mean_prob)

    if dispatch_impl == "auto":
        a = n_tokens * top_k
        dispatch_impl = (
            "einsum" if a * n_experts * cap <= _AUTO_EINSUM_LIMIT else "sort"
        )
    if dispatch_impl == "einsum":
        out = _dispatch_einsum(
            xt, expert_idx, gates, n_experts, cap, params, activation,
            expert_axis, x.dtype, tensor_axis,
        )
    elif dispatch_impl == "sort":
        out = _dispatch_sort(
            xt, expert_idx, gates, n_experts, cap, params, activation,
            expert_axis, x.dtype, tensor_axis,
        )
    else:
        raise ValueError(f"unknown dispatch_impl {dispatch_impl!r}")
    return out.astype(x.dtype).reshape(b, t, d), aux_loss
