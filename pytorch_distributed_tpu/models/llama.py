"""Llama-family transformer as pure functions over a params pytree.

Second model family (BASELINE.md configs 4-5: Llama-3 1B/8B FSDP). The
reference repo only ships GPT-2; this family exists to exercise the framework
at the benchmark scales with modern architecture: RMSNorm pre-norm, rotary
positions (no learned table), grouped-query attention, SwiGLU MLP, untied
LM head, no biases.

Same TPU-first structure as models/gpt2.py: stacked [L, ...] block params,
one ``lax.scan`` over layers, ``jax.checkpoint`` with a save-dots policy.

Params layout (E=n_embd, L=n_layer, V=vocab, F=inner_dim, H=n_head,
K=kv_heads, D=head_dim):
  wte [V, E]
  blocks/ln_attn {scale[L,E]}        blocks/ln_mlp {scale[L,E]}
  blocks/attn/{wq [L,E,H*D], wk [L,E,K*D], wv [L,E,K*D], wo [L,H*D,E]}
  blocks/mlp/{gate [L,E,F], up [L,E,F], down [L,F,E]}
  ln_f {scale[E]}
  lm_head [E, V]   (untied)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.models.gpt2 import _flash_kernel_active
from pytorch_distributed_tpu.ops.attention import multi_head_attention
from pytorch_distributed_tpu.ops.layer_scan import scan_layers
from pytorch_distributed_tpu.ops.layers import rms_norm
from pytorch_distributed_tpu.ops.remat import checkpoint_name
from pytorch_distributed_tpu.ops.rope import apply_rope, rope_angles
from pytorch_distributed_tpu.ops.tp import tp_copy, tp_reduce
from pytorch_distributed_tpu.utils.compat import vma_of

Params = dict[str, Any]


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    pdt = jnp.dtype(cfg.param_dtype)
    e, l, v, f = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.inner_dim
    h, k, d = cfg.n_head, cfg.kv_heads, cfg.head_dim

    keys = jax.random.split(key, 8)

    def normal(kk, shape, std=0.02):
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * std).astype(pdt)

    return {
        "wte": normal(keys[0], (v, e)),
        "blocks": {
            "ln_attn": {"scale": jnp.ones((l, e), pdt)},
            "attn": {
                "wq": normal(keys[1], (l, e, h * d)),
                "wk": normal(keys[2], (l, e, k * d)),
                "wv": normal(keys[3], (l, e, k * d)),
                "wo": normal(keys[4], (l, h * d, e)),
            },
            "ln_mlp": {"scale": jnp.ones((l, e), pdt)},
            "mlp": (
                {
                    "gate": normal(keys[5], (l, e, f)),
                    "up": normal(keys[6], (l, e, f)),
                    "down": normal(keys[7], (l, f, e)),
                }
                if not cfg.n_experts
                else {
                    # Switch-routed SwiGLU experts (ops/moe.py): per-layer
                    # router + stacked expert gate/up/down weights.
                    "router": normal(
                        jax.random.fold_in(keys[5], 1), (l, e, cfg.n_experts)
                    ),
                    "w_gate": normal(
                        keys[5], (l, cfg.n_experts, e, f)
                    ),
                    "w_in": normal(keys[6], (l, cfg.n_experts, e, f)),
                    "w_out": normal(keys[7], (l, cfg.n_experts, f, e)),
                }
            ),
        },
        "ln_f": {"scale": jnp.ones((e,), pdt)},
        "lm_head": normal(jax.random.fold_in(keys[0], 1), (e, v)),
    }


def _block(
    x, bp, cfg: ModelConfig, cos, sin, seq_axis=None, tensor_axis=None,
    expert_axis=None,
):
    """Returns (x, moe_aux_loss) — the aux term is zero for dense MLPs."""
    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    d = cfg.head_dim

    a = rms_norm(x, bp["ln_attn"], eps=eps)
    a = tp_copy(a, tensor_axis)
    q = checkpoint_name(a @ bp["attn"]["wq"].astype(a.dtype), "q")
    k = checkpoint_name(a @ bp["attn"]["wk"].astype(a.dtype), "k")
    v = checkpoint_name(a @ bp["attn"]["wv"].astype(a.dtype), "v")
    # Head counts derive from the (possibly tensor-sharded) kernel widths,
    # so the same code runs full and per-TP-shard.
    q = apply_rope(q.reshape(b, t, -1, d), cos, sin)
    k = apply_rope(k.reshape(b, t, -1, d), cos, sin)
    v = v.reshape(b, t, -1, d)
    a = multi_head_attention(
        q, k, v, impl=cfg.attention_impl, causal=True, deterministic=True,
        seq_axis=seq_axis, seq_impl=cfg.seq_impl,
    ).reshape(b, t, -1)
    if not _flash_kernel_active(cfg, t, seq_axis):
        # Pallas path: the kernel's o is already policy-saved (see gpt2.py).
        a = checkpoint_name(a, "attn_out")
    x = x + checkpoint_name(
        tp_reduce(a @ bp["attn"]["wo"].astype(a.dtype), tensor_axis),
        "attn_proj",
    )

    m = rms_norm(x, bp["ln_mlp"], eps=eps)
    if cfg.n_experts:
        from pytorch_distributed_tpu.ops.moe import moe_mlp

        m, aux = moe_mlp(
            m,
            bp["mlp"],
            activation=jax.nn.silu,
            capacity_factor=cfg.expert_capacity_factor,
            expert_axis=expert_axis,
            tensor_axis=tensor_axis,
            top_k=cfg.moe_top_k,
            dispatch_impl=cfg.moe_dispatch,
        )
        return x + m, aux
    aux = jnp.zeros((), jnp.float32)
    m = tp_copy(m, tensor_axis)
    gate = jax.nn.silu(
        checkpoint_name(m @ bp["mlp"]["gate"].astype(m.dtype), "mlp_gate")
    )
    up = checkpoint_name(m @ bp["mlp"]["up"].astype(m.dtype), "mlp_up")
    x = x + checkpoint_name(
        tp_reduce(
            (gate * up) @ bp["mlp"]["down"].astype(m.dtype), tensor_axis
        ),
        "mlp_proj",
    )
    return x, aux


def apply(
    params: Params,
    input_ids: jax.Array,
    cfg: ModelConfig,
    *,
    deterministic: bool = True,
    dropout_key: jax.Array | None = None,
    block_transform=None,
    seq_axis: str | None = None,
    tensor_axis: str | None = None,
    expert_axis: str | None = None,
    return_aux: bool = False,
    return_hidden: bool = False,
    prefetch_buffers: int = 0,
) -> jax.Array:
    """[B, T] int tokens -> [B, T, V] float32 logits. The llama family is
    dropout-free (cfg presets zero the pdrop fields), so train and eval
    forward passes coincide. ``block_transform`` — see models/gpt2.py.
    ``seq_axis`` — sequence-sharded (context-parallel) call: RoPE angles are
    offset by the shard's global start and attention runs the ring kernel.
    ``tensor_axis`` — explicit Megatron TP, see models/gpt2.py.
    ``expert_axis``/``return_aux`` — Switch-routed SwiGLU MoE
    (cfg.n_experts > 0, ops/moe.py); the aux value is the summed Switch
    load-balancing loss over layers (zero for dense configs)."""
    del dropout_key, deterministic
    b, t = input_ids.shape
    # Global length under sequence sharding (shards × local t): RoPE would
    # silently extrapolate past the trained context window otherwise.
    global_t = t * (jax.lax.psum(1, seq_axis) if seq_axis is not None else 1)
    if global_t > cfg.n_ctx:
        raise ValueError(
            f"sequence length {global_t} exceeds n_ctx {cfg.n_ctx}"
        )
    dtype = jnp.dtype(cfg.dtype)

    x = params["wte"][input_ids].astype(dtype)
    offset = (
        jax.lax.axis_index(seq_axis) * t if seq_axis is not None else 0
    )
    cos, sin = rope_angles(t, cfg.head_dim, cfg.rope_theta, offset=offset)

    def block_body(carry, bp, _extra):
        h, aux_sum = carry
        h, aux = _block(
            h, bp, cfg, cos, sin, seq_axis, tensor_axis, expert_axis
        )
        return (h, aux_sum + aux)

    # The aux carry must match the activations' varying axes under
    # shard_map (see models/gpt2.py).
    from pytorch_distributed_tpu.ops.tp import pvary_missing

    aux0 = pvary_missing(
        jnp.zeros((), jnp.float32),
        tuple(vma_of(x)),
    )
    x, aux_total = scan_layers(
        block_body, (x, aux0), params["blocks"],
        remat_mode=cfg.remat,
        block_transform=block_transform,
        prefetch_buffers=prefetch_buffers,
        unroll=cfg.scan_unroll,
    )
    if return_hidden:
        # Final-norm hidden states for the fused head+CE loss (see
        # models/gpt2.py apply docstring).
        out = final_norm(params, x, cfg)
    else:
        out = head(params, x, cfg)
    if return_aux:
        return out, aux_total
    return out


# -- phase functions (pipeline parallelism) — see models/gpt2.py -----------


def embed(
    params: Params,
    input_ids: jax.Array,
    cfg: ModelConfig,
    *,
    seq_axis: str | None = None,
) -> jax.Array:
    """``seq_axis``: sequence-sharded call — positions are rotary (applied
    inside run_blocks with the shard offset), so embedding is just the
    token lookup; only the GLOBAL length check changes."""
    t = input_ids.shape[1]
    global_t = t * (jax.lax.psum(1, seq_axis) if seq_axis is not None else 1)
    if global_t > cfg.n_ctx:
        raise ValueError(
            f"sequence length {global_t} exceeds n_ctx {cfg.n_ctx}"
        )
    return params["wte"][input_ids].astype(jnp.dtype(cfg.dtype))


def run_blocks(
    blocks: Params, x: jax.Array, cfg: ModelConfig, *, block_transform=None,
    return_aux: bool = False, tensor_axis: str | None = None,
    expert_axis: str | None = None, seq_axis: str | None = None,
    dropout_key: jax.Array | None = None,
    deterministic: bool = True, layer_offset=0,
    prefetch_buffers: int = 0,
):
    """See models/gpt2.py run_blocks — with ``return_aux=True`` returns
    (x, aux), the local layers' summed Switch load-balancing term;
    ``tensor_axis`` runs the blocks Megatron-style on local heads/columns
    (in-stage TP for the pipeline path); ``seq_axis`` runs attention
    sequence-parallel with RoPE offset by the shard's global start
    (in-stage seq). The dropout params are accepted for pipeline-path API
    parity and ignored — the llama family is dropout-free, like
    ``apply``."""
    del dropout_key, deterministic, layer_offset
    from pytorch_distributed_tpu.ops.tp import pvary_missing

    t = x.shape[1]
    offset = (
        jax.lax.axis_index(seq_axis) * t if seq_axis is not None else 0
    )
    cos, sin = rope_angles(t, cfg.head_dim, cfg.rope_theta, offset=offset)

    def block_body(carry, bp, _extra):
        h, aux_sum = carry
        h, aux = _block(
            h, bp, cfg, cos, sin, seq_axis, tensor_axis, expert_axis
        )
        return (h, aux_sum + aux)

    aux0 = pvary_missing(
        jnp.zeros((), jnp.float32),
        tuple(vma_of(x)),
    )
    x, aux_total = scan_layers(
        block_body, (x, aux0), blocks,
        remat_mode=cfg.remat,
        block_transform=block_transform,
        prefetch_buffers=prefetch_buffers,
    )
    if return_aux:
        return x, aux_total
    return x


def final_norm(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """ln_f alone — the hidden states the fused head+CE loss consumes
    (see models/gpt2.py final_norm)."""
    return rms_norm(x, params["ln_f"], eps=cfg.layer_norm_epsilon)


def head(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    x = final_norm(params, x, cfg)
    return jnp.einsum(
        "bte,ev->btv", x, params["lm_head"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    ).astype(jnp.dtype(cfg.logits_dtype))
