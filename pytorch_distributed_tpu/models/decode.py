"""KV-cache autoregressive decoding for both model families.

The reference repo has no inference path at all — training only. A complete
framework needs one: this module adds prefill + single-token decode over a
preallocated KV cache, and a jit-compiled ``generate`` loop (greedy or
temperature sampling), for gpt2 and llama params produced by
``models.get_model(cfg)`` — dense AND MoE variants (routing is per-token
and cache-free, see ``_moe_mlp``). ``generate_tp`` runs the same loop
tensor-parallel over a "tensor" mesh: Megatron-sharded params, local-head
attention against a local-head cache shard (1/tp of the cache HBM), one
psum per row-parallel projection.

Design (TPU-first):
- The cache is a pytree of stacked per-layer tensors ``k/v [L, B, S, Hkv, D]``
  preallocated at ``max_len`` — static shapes throughout; the current length
  ``pos`` is a traced scalar. ``forward`` handles both prefill (T = prompt
  length) and decode (T = 1) with one code path: new keys/values are
  ``dynamic_update_slice``d into the cache at ``pos`` and attention masks
  key positions ``> pos + i`` (padding beyond the write point is masked
  out, so stale cache contents are never read).
- Layers run under the same ``lax.scan``-over-stacked-params structure as
  training; the per-layer cache slices ride the scan's xs/ys.
- Attention here is the naive einsum path in f32: decode is matmul-light
  ([B, H, T, S] with T = 1), so flash-kernel dispatch is pointless.
- The generate loop is a ``lax.fori_loop`` over steps inside one jit; the
  output buffer is preallocated [B, prompt + max_new] and updated in place.

No dropout (inference), no remat (nothing to save).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.ops.layers import (
    activation,
    dense,
    layer_norm,
    rms_norm,
)
from pytorch_distributed_tpu.ops.rope import apply_rope, rope_angles

Params = dict[str, Any]
Cache = dict[str, jax.Array]


def init_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype=None,
    n_kv: int | None = None,
) -> Cache:
    """Preallocate a [L, B, max_len, Hkv, D] key/value cache pair.
    ``n_kv`` overrides the head count for tensor-parallel decode, where
    each shard caches only its LOCAL kv heads (1/tp of the HBM)."""
    if max_len > cfg.n_ctx:
        raise ValueError(f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}")
    dtype = jnp.dtype(dtype or cfg.dtype)
    shape = (
        cfg.n_layer, batch, max_len, n_kv or cfg.kv_heads, cfg.head_dim
    )
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_attention(q, ck, cv, pos):
    """q [B, T, H, D] against the full cache [B, S, Hkv, D]; queries sit at
    global positions pos..pos+T-1, keys j are valid iff j <= pos + i."""
    b, t, h, d = q.shape
    s, hkv = ck.shape[1], ck.shape[2]
    if hkv != h:
        rep = h // hkv
        ck = jnp.repeat(ck, rep, axis=2)
        cv = jnp.repeat(cv, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, ck, preferred_element_type=jnp.float32
    ) / (d**0.5)
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
    scores = jnp.where(kpos <= qpos, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    return jnp.einsum("bhts,bshd->bthd", w, cv)


def _write(cache_layer, new, pos):
    """Insert new [B, T, Hkv, D] at time offset pos."""
    return jax.lax.dynamic_update_slice(
        cache_layer, new.astype(cache_layer.dtype), (0, pos, 0, 0)
    )


def _moe_mlp(m, mlp_params, cfg, act, tensor_axis=None):
    """Routed MLP for decode: top-1/top-k routing is per-token and
    cache-free, so only the MLP call differs from training. Capacity is
    set to the no-drop bound (cap = k * tokens): a dropped token at
    inference would silently zero its MLP contribution, and at decode
    shapes the slack is negligible. ``tensor_axis``: Megatron TP inside
    each expert (the training EP x TP placement, ops/moe._expert_compute)
    — routing runs on replicated activations so it agrees across shards,
    and the in-expert tp_reduce restores the full output."""
    from pytorch_distributed_tpu.ops.moe import moe_mlp

    out, _ = moe_mlp(
        m,
        mlp_params,
        activation=act,
        capacity_factor=float(cfg.n_experts),
        top_k=cfg.moe_top_k,
        dispatch_impl=cfg.moe_dispatch,
        tensor_axis=tensor_axis,
    )
    return out


def _gpt2_block(x, bp, ck, cv, pos, cfg, tensor_axis=None):
    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    a = layer_norm(x, bp["ln_1"], eps=eps)
    qkv = dense(a, bp["attn"]["c_attn"])  # [B, T, 3, H(/tp), D]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    ck, cv = _write(ck, k, pos), _write(cv, v, pos)
    a = _cached_attention(q, ck, cv, pos).reshape(b, t, -1)
    x = x + dense(a, bp["attn"]["c_proj"], tp_reduce_axis=tensor_axis)
    m = layer_norm(x, bp["ln_2"], eps=eps)
    act = activation(cfg.activation_function)
    if cfg.n_experts:
        m = _moe_mlp(m, bp["mlp"], cfg, act, tensor_axis)
        return x + m, ck, cv
    m = act(dense(m, bp["mlp"]["c_fc"]))
    return x + dense(m, bp["mlp"]["c_proj"], tp_reduce_axis=tensor_axis), ck, cv


def _llama_block(x, bp, ck, cv, pos, cfg, cos, sin, tensor_axis=None):
    from pytorch_distributed_tpu.ops.tp import tp_reduce

    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    d = cfg.head_dim
    a = rms_norm(x, bp["ln_attn"], eps=eps)
    q = apply_rope((a @ bp["attn"]["wq"].astype(a.dtype)).reshape(b, t, -1, d), cos, sin)
    k = apply_rope((a @ bp["attn"]["wk"].astype(a.dtype)).reshape(b, t, -1, d), cos, sin)
    v = (a @ bp["attn"]["wv"].astype(a.dtype)).reshape(b, t, -1, d)
    ck, cv = _write(ck, k, pos), _write(cv, v, pos)
    a = _cached_attention(q, ck, cv, pos).reshape(b, t, -1)
    x = x + tp_reduce(a @ bp["attn"]["wo"].astype(a.dtype), tensor_axis)
    m = rms_norm(x, bp["ln_mlp"], eps=eps)
    if cfg.n_experts:
        return x + _moe_mlp(m, bp["mlp"], cfg, jax.nn.silu, tensor_axis), ck, cv
    gate = jax.nn.silu(m @ bp["mlp"]["gate"].astype(m.dtype))
    up = m @ bp["mlp"]["up"].astype(m.dtype)
    down = (gate * up) @ bp["mlp"]["down"].astype(m.dtype)
    return x + tp_reduce(down, tensor_axis), ck, cv


def forward(
    params: Params,
    input_ids: jax.Array,  # [B, T] — full prompt (prefill) or one token
    cfg: ModelConfig,
    cache: Cache,
    pos: jax.Array | int,  # tokens already in the cache
    *,
    tensor_axis: str | None = None,
) -> tuple[jax.Array, Cache]:
    """Run T tokens at positions pos..pos+T-1. Returns ([B, T, V] logits,
    updated cache). MoE configs route each token through the expert MLPs
    (no-drop capacity — see ``_moe_mlp``); routing is stateless, so the
    KV cache is untouched by the choice of MLP.

    ``tensor_axis``: set when called inside shard_map with block params
    sharded Megatron-style (tensor-parallel decode): attention runs on
    the LOCAL heads against a local-head cache shard, row-parallel
    projections psum over the axis, and the logits come back replicated.
    """
    b, t = input_ids.shape
    dtype = jnp.dtype(cfg.dtype)
    pos = jnp.asarray(pos, jnp.int32)

    if cfg.family == "gpt2":
        wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos, t, axis=0)
        x = (params["wte"][input_ids] + wpe).astype(dtype)
        block = partial(_gpt2_block, cfg=cfg, tensor_axis=tensor_axis)
    elif cfg.family == "llama":
        x = params["wte"][input_ids].astype(dtype)
        cos, sin = rope_angles(
            t, cfg.head_dim, cfg.rope_theta, offset=pos
        )
        block = partial(
            _llama_block, cfg=cfg, cos=cos, sin=sin,
            tensor_axis=tensor_axis,
        )
    else:
        raise KeyError(f"unknown model family {cfg.family!r}")

    def scan_body(x, xs):
        bp, ck_l, cv_l = xs
        x, ck_l, cv_l = block(x, bp, ck_l, cv_l, pos)
        return x, (ck_l, cv_l)

    x, (ck, cv) = jax.lax.scan(
        scan_body, x, (params["blocks"], cache["k"], cache["v"])
    )

    from pytorch_distributed_tpu.models import get_model

    logits = get_model(cfg).head(params, x, cfg)
    return logits, {"k": ck, "v": cv}


def _sample(logits, temperature, key, top_k=None, top_p=None):
    """[B, V] -> [B] next tokens. temperature 0 = greedy; top_k restricts
    sampling to the k highest-probability tokens; top_p (nucleus) restricts
    it to the smallest set whose probability mass reaches p. Given BOTH,
    top-k applies first and the nucleus is taken within it (HF semantics).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is None and top_p is None:
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
    k = top_k if top_k is not None else logits.shape[-1]
    vals, idx = jax.lax.top_k(logits, k)  # [B, k], sorted desc
    if top_p is not None:
        # Keep tokens whose CUMULATIVE mass (within the top-k support)
        # before them is < p — the argmax token always survives.
        probs = jax.nn.softmax(vals, axis=-1)
        cum_before = jnp.cumsum(probs, axis=-1) - probs
        vals = jnp.where(cum_before < top_p, vals, -jnp.inf)
    choice = jax.random.categorical(key, vals, axis=-1)  # [B]
    return jnp.take_along_axis(
        idx, choice[:, None], axis=-1
    )[:, 0].astype(jnp.int32)


def _generate_impl(
    params, prompt, cfg, max_new_tokens, temperature, key,
    max_len, top_k, top_p, tensor_axis=None, n_kv=None,
):
    """Shared generation body: prefill over the prompt, then a fori_loop
    of single-token decode steps against the cache. Runs plain (generate)
    or inside shard_map (generate_tp)."""
    b, tp = prompt.shape
    total = tp + max_new_tokens
    max_len = max_len or total
    # key is never None here: _check_sample_args owns the greedy-path
    # dummy-key substitution for every entry point.

    cache = init_cache(cfg, b, max_len, n_kv=n_kv)
    if tensor_axis is not None:
        # The cache carries tensor-sharded values (local-head K/V); its
        # zero init must be typed varying over the axis or the fori_loop
        # carry types mismatch under check_vma.
        from pytorch_distributed_tpu.ops.tp import pvary_missing

        cache = jax.tree.map(
            lambda c: pvary_missing(c, (tensor_axis,)), cache
        )
    logits, cache = forward(
        params, prompt, cfg, cache, 0, tensor_axis=tensor_axis
    )
    next_tok = _sample(logits[:, -1], temperature, key, top_k, top_p)

    out = jnp.zeros((b, total), jnp.int32)
    out = jax.lax.dynamic_update_slice(out, prompt.astype(jnp.int32), (0, 0))
    out = out.at[:, tp].set(next_tok)

    def step(i, carry):
        out, cache, tok = carry
        pos = tp + i
        logits, cache = forward(
            params, tok[:, None], cfg, cache, pos, tensor_axis=tensor_axis
        )
        nxt = _sample(
            logits[:, -1], temperature, jax.random.fold_in(key, i), top_k,
            top_p,
        )
        out = out.at[:, pos + 1].set(nxt)
        return out, cache, nxt

    out, _, _ = jax.lax.fori_loop(
        0, max_new_tokens - 1, step, (out, cache, next_tok)
    )
    return out


# repolint: allow(jit-donation-decision) — params are the serving
# weights, reused by every generate call; the cache is jit-internal.
@partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "temperature", "max_len", "top_k", "top_p"
    ),
)
def generate(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Autoregressive generation: returns [B, Tp + max_new_tokens].

    One compiled program: prefill over the prompt, then a fori_loop of
    single-token decode steps against the cache.
    """
    early, key = _check_sample_args(prompt, max_new_tokens, temperature, key)
    if early is not None:
        return early
    return _generate_impl(
        params, prompt, cfg, max_new_tokens, temperature, key,
        max_len, top_k, top_p,
    )


def generate_tp(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    mesh_cfg,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Tensor-parallel generation over a "tensor" mesh (meshed decode —
    models whose weights exceed one chip sample across tp shards).

    Block params shard Megatron-style per parallel/sharding.py's rule
    table (the SAME layout training leaves them in, so a trained sharded
    state decodes with no resharding); each shard runs attention on its
    LOCAL heads against a local-head KV cache (1/tp of the cache HBM),
    row-parallel projections psum over the axis, and the replicated
    logits sample identically on every shard.
    """
    tp_size = mesh_cfg.tensor
    if tp_size <= 1:
        raise ValueError("generate_tp needs mesh_cfg.tensor > 1")
    for ax in ("data", "fsdp", "seq", "pipe", "expert"):
        if getattr(mesh_cfg, ax) > 1:
            raise NotImplementedError(
                f"generate_tp supports a tensor-only mesh (got {ax}="
                f"{getattr(mesh_cfg, ax)})"
            )
    if cfg.n_experts and cfg.inner_dim % tp_size:
        raise ValueError(
            f"tensor={tp_size} must divide the MoE expert hidden dim "
            f"inner_dim={cfg.inner_dim} (experts run Megatron TP on F)"
        )
    if cfg.n_head % tp_size or cfg.kv_heads % tp_size:
        raise ValueError(
            f"tensor={tp_size} must divide n_head={cfg.n_head} and "
            f"kv_heads={cfg.kv_heads}"
        )
    early, key = _check_sample_args(prompt, max_new_tokens, temperature, key)
    if early is not None:
        return early

    fn, shardings = _tp_generate_compiled(
        cfg, mesh_cfg, max_new_tokens, temperature, max_len, top_k, top_p
    )
    # device_put with the target shardings is a no-op when params are
    # already placed, so repeat calls only pay the (cached) jit lookup.
    return fn(jax.device_put(params, shardings), prompt, key)


def _check_sample_args(prompt, max_new_tokens, temperature, key):
    """Shared generate-entry validation. Returns (early_out, key): when
    ``early_out`` is not None the caller returns it unchanged (nothing to
    generate — the write of the first sampled token would statically index
    out of bounds); otherwise ``key`` is non-None (greedy paths get a
    dummy, unused by sampling)."""
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return prompt.astype(jnp.int32), key
    if temperature > 0.0 and key is None:
        raise ValueError("temperature sampling requires a PRNG key")
    if key is None:
        key = jax.random.key(0)
    return None, key


def _mesh_param_shardings(cfg, mesh_cfg):
    """(mesh, partition-spec tree, NamedSharding tree) for decode params
    under ``mesh_cfg`` — shared by the meshed decode paths so spec
    derivation cannot diverge between them. Specs come from the abstract
    init, so no concrete params are needed (lru_cache-friendly)."""
    from jax.sharding import NamedSharding

    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_tpu.parallel.sharding import (
        param_partition_specs,
    )

    mesh = make_mesh(mesh_cfg)
    abstract = jax.eval_shape(
        lambda k: get_model(cfg).init(k, cfg), jax.random.key(0)
    )
    p_specs = param_partition_specs(abstract, mesh_cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        p_specs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )
    return mesh, p_specs, shardings


def generate_fsdp(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    mesh_cfg,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Decode from ZeRO-3-sharded params over an "fsdp" mesh — sample IN
    PLACE from the layout full-shard training leaves the weights in (no
    resharding, and per-chip param HBM stays 1/fsdp of the model).

    Unlike ``generate_tp`` (shard_map + hand-placed psums), this is the
    auto path: the decode loop is jitted with the params carrying their
    full_shard NamedShardings and XLA's SPMD partitioner inserts the
    gathers. The stacked [L, ...] block leaves shard a WEIGHT dim (never
    L — parallel/sharding.py), so inside the scan-over-layers each
    iteration all_gathers only its own layer slice: one layer's gathered
    weights are live at a time, the same per-block-gather discipline
    full-shard training uses. MoE configs work unchanged (routing and
    dispatch are ordinary auto-sharded ops here).
    """
    if mesh_cfg.fsdp <= 1:
        raise ValueError("generate_fsdp needs mesh_cfg.fsdp > 1")
    for ax in ("data", "tensor", "seq", "pipe", "expert"):
        if getattr(mesh_cfg, ax) > 1:
            raise NotImplementedError(
                f"generate_fsdp supports an fsdp-only mesh (got {ax}="
                f"{getattr(mesh_cfg, ax)}); combine with generate_tp's "
                "tensor sharding is future surface"
            )
    if mesh_cfg.strategy != "full_shard":
        raise ValueError(
            "generate_fsdp decodes from full_shard (ZeRO-3) param "
            f"layouts; strategy={mesh_cfg.strategy!r} keeps params "
            "replicated — plain generate already covers it"
        )
    early, key = _check_sample_args(prompt, max_new_tokens, temperature, key)
    if early is not None:
        return early

    fn, shardings = _fsdp_generate_compiled(
        cfg, mesh_cfg, max_new_tokens, temperature, max_len, top_k, top_p
    )
    return fn(jax.device_put(params, shardings), prompt, key)


@functools.lru_cache(maxsize=None)
def _fsdp_generate_compiled(
    cfg, mesh_cfg, max_new_tokens, temperature, max_len, top_k, top_p
):
    """(jitted auto-path generate fn, full_shard param shardings) for one
    static config — cached like _tp_generate_compiled."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, _, shardings = _mesh_param_shardings(cfg, mesh_cfg)
    replicated = NamedSharding(mesh, P())

    def body(params, prompt, key):
        return _generate_impl(
            params, prompt, cfg, max_new_tokens, temperature, key,
            max_len, top_k, top_p,
        )

    # repolint: allow(jit-donation-decision) — sharded serving weights
    # are reused across generate_fsdp calls; nothing here is consumed.
    fn = jax.jit(
        body,
        in_shardings=(shardings, replicated, replicated),
        out_shardings=replicated,
    )
    return fn, shardings


@functools.lru_cache(maxsize=None)
def _tp_generate_compiled(
    cfg, mesh_cfg, max_new_tokens, temperature, max_len, top_k, top_p
):
    """(jitted shard_map generate fn, param shardings) for one static
    config — cached so a serving loop does not retrace/recompile the
    whole prefill+fori_loop program per generate_tp call (both config
    dataclasses are frozen, hence hashable). Param specs are derived
    from the abstract init so the cache needs no concrete params."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.utils.compat import shard_map

    mesh, p_specs, shardings = _mesh_param_shardings(cfg, mesh_cfg)

    def body(params, prompt, key):
        return _generate_impl(
            params, prompt, cfg, max_new_tokens, temperature, key,
            max_len, top_k, top_p,
            tensor_axis="tensor", n_kv=cfg.kv_heads // mesh_cfg.tensor,
        )

    smapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(p_specs, P(), P()),
        out_specs=P(),
        check_vma=True,
    )
    # repolint: allow(jit-donation-decision) — TP serving weights are
    # reused across generate_tp calls; the KV cache is jit-internal.
    return jax.jit(smapped), shardings
