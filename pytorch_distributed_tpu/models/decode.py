"""KV-cache autoregressive decoding for both model families.

The reference repo has no inference path at all — training only. A complete
framework needs one: this module adds prefill + single-token decode over a
preallocated KV cache, and a jit-compiled ``generate`` loop (greedy or
temperature sampling), for gpt2 and llama params produced by
``models.get_model(cfg)``.

Design (TPU-first):
- The cache is a pytree of stacked per-layer tensors ``k/v [L, B, S, Hkv, D]``
  preallocated at ``max_len`` — static shapes throughout; the current length
  ``pos`` is a traced scalar. ``forward`` handles both prefill (T = prompt
  length) and decode (T = 1) with one code path: new keys/values are
  ``dynamic_update_slice``d into the cache at ``pos`` and attention masks
  key positions ``> pos + i`` (padding beyond the write point is masked
  out, so stale cache contents are never read).
- Layers run under the same ``lax.scan``-over-stacked-params structure as
  training; the per-layer cache slices ride the scan's xs/ys.
- Attention here is the naive einsum path in f32: decode is matmul-light
  ([B, H, T, S] with T = 1), so flash-kernel dispatch is pointless.
- The generate loop is a ``lax.fori_loop`` over steps inside one jit; the
  output buffer is preallocated [B, prompt + max_new] and updated in place.

No dropout (inference), no remat (nothing to save).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.ops.layers import (
    activation,
    dense,
    layer_norm,
    rms_norm,
)
from pytorch_distributed_tpu.ops.rope import apply_rope, rope_angles

Params = dict[str, Any]
Cache = dict[str, jax.Array]


def init_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype=None
) -> Cache:
    """Preallocate a [L, B, max_len, Hkv, D] key/value cache pair."""
    if max_len > cfg.n_ctx:
        raise ValueError(f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}")
    dtype = jnp.dtype(dtype or cfg.dtype)
    shape = (cfg.n_layer, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _cached_attention(q, ck, cv, pos):
    """q [B, T, H, D] against the full cache [B, S, Hkv, D]; queries sit at
    global positions pos..pos+T-1, keys j are valid iff j <= pos + i."""
    b, t, h, d = q.shape
    s, hkv = ck.shape[1], ck.shape[2]
    if hkv != h:
        rep = h // hkv
        ck = jnp.repeat(ck, rep, axis=2)
        cv = jnp.repeat(cv, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, ck, preferred_element_type=jnp.float32
    ) / (d**0.5)
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
    scores = jnp.where(kpos <= qpos, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    return jnp.einsum("bhts,bshd->bthd", w, cv)


def _write(cache_layer, new, pos):
    """Insert new [B, T, Hkv, D] at time offset pos."""
    return jax.lax.dynamic_update_slice(
        cache_layer, new.astype(cache_layer.dtype), (0, pos, 0, 0)
    )


def _gpt2_block(x, bp, ck, cv, pos, cfg):
    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    a = layer_norm(x, bp["ln_1"], eps=eps)
    qkv = dense(a, bp["attn"]["c_attn"])  # [B, T, 3, H, D]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    ck, cv = _write(ck, k, pos), _write(cv, v, pos)
    a = _cached_attention(q, ck, cv, pos).reshape(b, t, -1)
    x = x + dense(a, bp["attn"]["c_proj"])
    m = layer_norm(x, bp["ln_2"], eps=eps)
    m = activation(cfg.activation_function)(dense(m, bp["mlp"]["c_fc"]))
    return x + dense(m, bp["mlp"]["c_proj"]), ck, cv


def _llama_block(x, bp, ck, cv, pos, cfg, cos, sin):
    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    d = cfg.head_dim
    a = rms_norm(x, bp["ln_attn"], eps=eps)
    q = apply_rope((a @ bp["attn"]["wq"].astype(a.dtype)).reshape(b, t, -1, d), cos, sin)
    k = apply_rope((a @ bp["attn"]["wk"].astype(a.dtype)).reshape(b, t, -1, d), cos, sin)
    v = (a @ bp["attn"]["wv"].astype(a.dtype)).reshape(b, t, -1, d)
    ck, cv = _write(ck, k, pos), _write(cv, v, pos)
    a = _cached_attention(q, ck, cv, pos).reshape(b, t, -1)
    x = x + a @ bp["attn"]["wo"].astype(a.dtype)
    m = rms_norm(x, bp["ln_mlp"], eps=eps)
    gate = jax.nn.silu(m @ bp["mlp"]["gate"].astype(m.dtype))
    up = m @ bp["mlp"]["up"].astype(m.dtype)
    return x + (gate * up) @ bp["mlp"]["down"].astype(m.dtype), ck, cv


def forward(
    params: Params,
    input_ids: jax.Array,  # [B, T] — full prompt (prefill) or one token
    cfg: ModelConfig,
    cache: Cache,
    pos: jax.Array | int,  # tokens already in the cache
) -> tuple[jax.Array, Cache]:
    """Run T tokens at positions pos..pos+T-1. Returns ([B, T, V] logits,
    updated cache)."""
    if cfg.n_experts:
        raise NotImplementedError("decode does not support MoE configs yet")
    b, t = input_ids.shape
    dtype = jnp.dtype(cfg.dtype)
    pos = jnp.asarray(pos, jnp.int32)

    if cfg.family == "gpt2":
        wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos, t, axis=0)
        x = (params["wte"][input_ids] + wpe).astype(dtype)
        block = partial(_gpt2_block, cfg=cfg)
    elif cfg.family == "llama":
        x = params["wte"][input_ids].astype(dtype)
        cos, sin = rope_angles(
            t, cfg.head_dim, cfg.rope_theta, offset=pos
        )
        block = partial(_llama_block, cfg=cfg, cos=cos, sin=sin)
    else:
        raise KeyError(f"unknown model family {cfg.family!r}")

    def scan_body(x, xs):
        bp, ck_l, cv_l = xs
        x, ck_l, cv_l = block(x, bp, ck_l, cv_l, pos)
        return x, (ck_l, cv_l)

    x, (ck, cv) = jax.lax.scan(
        scan_body, x, (params["blocks"], cache["k"], cache["v"])
    )

    from pytorch_distributed_tpu.models import get_model

    logits = get_model(cfg).head(params, x, cfg)
    return logits, {"k": ck, "v": cv}


def _sample(logits, temperature, key, top_k=None, top_p=None):
    """[B, V] -> [B] next tokens. temperature 0 = greedy; top_k restricts
    sampling to the k highest-probability tokens; top_p (nucleus) restricts
    it to the smallest set whose probability mass reaches p. Given BOTH,
    top-k applies first and the nucleus is taken within it (HF semantics).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is None and top_p is None:
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
    k = top_k if top_k is not None else logits.shape[-1]
    vals, idx = jax.lax.top_k(logits, k)  # [B, k], sorted desc
    if top_p is not None:
        # Keep tokens whose CUMULATIVE mass (within the top-k support)
        # before them is < p — the argmax token always survives.
        probs = jax.nn.softmax(vals, axis=-1)
        cum_before = jnp.cumsum(probs, axis=-1) - probs
        vals = jnp.where(cum_before < top_p, vals, -jnp.inf)
    choice = jax.random.categorical(key, vals, axis=-1)  # [B]
    return jnp.take_along_axis(
        idx, choice[:, None], axis=-1
    )[:, 0].astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "temperature", "max_len", "top_k", "top_p"
    ),
)
def generate(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Autoregressive generation: returns [B, Tp + max_new_tokens].

    One compiled program: prefill over the prompt, then a fori_loop of
    single-token decode steps against the cache.
    """
    b, tp = prompt.shape
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        # Nothing to generate: the prompt IS the output (the write of the
        # first sampled token below would statically index out of bounds).
        return prompt.astype(jnp.int32)
    total = tp + max_new_tokens
    max_len = max_len or total
    if temperature > 0.0 and key is None:
        raise ValueError("temperature sampling requires a PRNG key")
    if key is None:
        key = jax.random.key(0)  # unused on the greedy path

    cache = init_cache(cfg, b, max_len)
    logits, cache = forward(params, prompt, cfg, cache, 0)
    next_tok = _sample(logits[:, -1], temperature, key, top_k, top_p)

    out = jnp.zeros((b, total), jnp.int32)
    out = jax.lax.dynamic_update_slice(out, prompt.astype(jnp.int32), (0, 0))
    out = out.at[:, tp].set(next_tok)

    def step(i, carry):
        out, cache, tok = carry
        pos = tp + i
        logits, cache = forward(params, tok[:, None], cfg, cache, pos)
        nxt = _sample(
            logits[:, -1], temperature, jax.random.fold_in(key, i), top_k,
            top_p,
        )
        out = out.at[:, pos + 1].set(nxt)
        return out, cache, nxt

    out, _, _ = jax.lax.fori_loop(
        0, max_new_tokens - 1, step, (out, cache, next_tok)
    )
    return out
