"""KV-cache autoregressive decoding for both model families.

The reference repo has no inference path at all — training only. A complete
framework needs one: this module adds prefill + single-token decode over a
preallocated KV cache, and the ``generate`` / ``generate_tp`` /
``generate_fsdp`` entry points for gpt2 and llama params produced by
``models.get_model(cfg)`` — dense AND MoE variants (routing is per-token
and cache-free, see ``_moe_mlp``).

Since the serving PR, the public ``generate*`` entry points are thin compat
shims over ``serving.engine.DecodeEngine`` — the two-program
(prefill / decode-step) serving fast path with a DONATED, pooled KV cache,
bucketed prompt compilation, and traced sampling scalars. The original
one-jit monolithic programs survive as ``generate_monolithic`` /
``generate_tp_monolithic`` / ``generate_fsdp_monolithic``: the reference
implementations the engine is pinned bit-equal against
(tests/test_serving.py), and the "per-call path" leg of
scripts/decode_bench.py.

Design (TPU-first):
- The cache is a pytree of stacked per-layer tensors ``k/v [L, B, S, Hkv, D]``
  preallocated at ``max_len`` — static shapes throughout; the current length
  ``pos`` is a traced scalar. ``forward`` handles both prefill (T = prompt
  length) and decode (T = 1) with one code path: new keys/values are
  ``dynamic_update_slice``d into the cache at ``pos`` and attention masks
  key positions ``> pos + i`` (padding beyond the write point is masked
  out, so stale cache contents are never read — the invariant that makes
  both prompt bucketing and dirty-buffer cache donation sound).
- Layers run under the shared ``ops/layer_scan.scan_layers`` scan-over-
  stacked-params (``collect_ys=True`` carries the per-layer cache slices),
  so the windowed double-buffer prefetch schedule training uses applies to
  ZeRO-3 decode as well (``block_transform`` + ``prefetch_buffers``).
- Attention here is the naive einsum path in f32: decode is matmul-light
  ([B, H, T, S] with T = 1), so flash-kernel dispatch is pointless.
- Sampling params (``temperature``/``top_k``/``top_p``) are TRACED runtime
  scalars on every path — a serving loop changing sampling configs never
  recompiles; only greedy-vs-sampled is a static bool (temperature 0 needs
  a different program shape: no division, no sort, no key).

No dropout (inference), no remat (nothing to save).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.ops.layer_scan import scan_layers
from pytorch_distributed_tpu.ops.layers import (
    activation,
    dense,
    layer_norm,
    rms_norm,
)
from pytorch_distributed_tpu.ops.rope import apply_rope, rope_angles

Params = dict[str, Any]
Cache = dict[str, jax.Array]


def init_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype=None,
    n_kv: int | None = None,
) -> Cache:
    """Preallocate a [L, B, max_len, Hkv, D] key/value cache pair.
    ``n_kv`` overrides the head count for tensor-parallel decode, where
    each shard caches only its LOCAL kv heads (1/tp of the HBM)."""
    if max_len > cfg.n_ctx:
        raise ValueError(f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}")
    dtype = jnp.dtype(dtype or cfg.dtype)
    shape = (
        cfg.n_layer, batch, max_len, n_kv or cfg.kv_heads, cfg.head_dim
    )
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def init_paged_cache(
    cfg: ModelConfig, pool_pages: int, page_size: int, dtype=None,
    n_kv: int | None = None, kv_quant: str = "none",
) -> Cache:
    """Preallocate a PAGED [L, pool_pages, page_size, Hkv, D] key/value
    pool pair (serving/block_pool.py owns the host-side allocation; page
    0 is the reserved scratch page). ``n_kv`` as in ``init_cache``.

    ``kv_quant="int8"``: the value pools are int8 and two f32 scale
    pools ``k_scale``/``v_scale`` of [L, pool_pages, page_size, Hkv]
    ride alongside — one symmetric scale per written token per KV head
    (ops/quant.py: per-token granularity is what keeps incremental page
    writes sound), cutting a page's bytes to ~(D + 4)/(4D) of the f32
    pool."""
    if kv_quant not in ("none", "int8"):
        raise ValueError(
            f"kv_quant must be 'none' or 'int8', got {kv_quant!r}"
        )
    dtype = jnp.dtype(dtype or cfg.dtype)
    shape = (
        cfg.n_layer, pool_pages, page_size, n_kv or cfg.kv_heads,
        cfg.head_dim,
    )
    if kv_quant == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(shape[:-1], jnp.float32),
            "v_scale": jnp.ones(shape[:-1], jnp.float32),
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def gather_pages(cache_layer: jax.Array, block_tables: jax.Array):
    """[P, page, ...] pool + [B, n_pages] tables -> the [B, S, ...]
    contiguous per-row view dense attention expects (S = n_pages * page;
    trailing dims pass through, so int8 value pools [P, page, Hkv, D]
    and their scale pools [P, page, Hkv] gather through the same code).
    Unallocated table entries point at the scratch page — garbage the
    ``pos`` mask already excludes, exactly like a dense row's unwritten
    tail. This is the XLA fallback the CPU rig runs; the Pallas decode
    kernel (ops/paged_kernel.py) reads pages in place instead."""
    b, n_pages = block_tables.shape
    page = cache_layer.shape[1]
    return cache_layer[block_tables].reshape(
        (b, n_pages * page) + cache_layer.shape[2:]
    )


def _cached_attention(q, kv, pos, block_tables=None,
                      paged_impl="gather", kv_quant="none"):
    """q [B, T, H, D] against the full cache ``kv`` ({"k", "v"} leaves
    [B, S, Hkv, D]); queries sit at
    global positions pos..pos+T-1, keys j are valid iff j <= pos + i.
    ``pos`` is a scalar (every row at the same position — the single-request
    paths) or a [B] vector (slot-batched decode: each row carries its own
    position, so each row's mask — and therefore which cache rows it can
    ever read — is independent of its neighbours).

    ``block_tables`` [B, n_pages] switches to the PAGED cache layout
    (k/v are [P, page, Hkv, D] pools): the gather fallback materialises
    the per-row view and runs the identical masked math (bit-equal to the
    dense path wherever the valid positions hold the same values); for
    single-token decode, ``paged_impl`` of "kernel"/"kernel_interpret"
    dispatches the Pallas paged-attention kernel instead, which reads
    pages in place and skips pages past each row's depth.

    ``kv_quant="int8"`` (paged only): ``kv`` additionally carries
    ``k_scale``/``v_scale`` pools; the gather path dequantizes the
    gathered view (one int8->f32 convert per K and V — the audit's q8
    cast budget counts them) and runs the identical masked math, the
    kernel path dequantizes page blocks in VMEM (dequant-in-kernel —
    HBM only ever moves int8 pages + scales)."""
    ck, cv = kv["k"], kv["v"]
    if block_tables is not None and q.shape[1] == 1 and (
        paged_impl in ("kernel", "kernel_interpret")
    ):
        from pytorch_distributed_tpu.ops.paged_kernel import (
            paged_decode_attention,
        )

        scales = (
            (kv["k_scale"], kv["v_scale"]) if kv_quant == "int8"
            else (None, None)
        )
        out = paged_decode_attention(
            q[:, 0], ck, cv, block_tables, pos,
            k_scales=scales[0], v_scales=scales[1],
            interpret=paged_impl == "kernel_interpret",
        )
        return out[:, None]
    if block_tables is not None:
        ck = gather_pages(ck, block_tables)
        cv = gather_pages(cv, block_tables)
        if kv_quant == "int8":
            from pytorch_distributed_tpu.ops.quant import dequantize_kv

            ck = dequantize_kv(
                ck, gather_pages(kv["k_scale"], block_tables), q.dtype
            )
            cv = dequantize_kv(
                cv, gather_pages(kv["v_scale"], block_tables), q.dtype
            )
    b, t, h, d = q.shape
    s, hkv = ck.shape[1], ck.shape[2]
    if hkv != h:
        rep = h // hkv
        ck = jnp.repeat(ck, rep, axis=2)
        cv = jnp.repeat(cv, rep, axis=2)
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, ck, preferred_element_type=jnp.float32
    ) / (d**0.5)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
    if getattr(pos, "ndim", 0):  # per-row positions -> [B, 1, T, S] mask
        valid = kpos[None] <= pos[:, None, None] + qpos[None]
        scores = jnp.where(valid[:, None], scores, -1e30)
    else:
        scores = jnp.where(kpos <= pos + qpos, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(cv.dtype)
    return jnp.einsum("bhts,bshd->bthd", w, cv)


def _write(cache_layer, new, pos, block_tables=None):
    """Insert new [B, T, Hkv, D] at time offset pos. A [B] vector pos
    writes each row at ITS OWN offset (slot-batched decode) via a vmapped
    per-row update — pure data movement either way, so a row written at
    pos[b] holds bit-identical values to the scalar-pos write at the same
    offset.

    With ``block_tables`` [B, n_pages] the cache layer is a PAGED pool
    [P, page, Hkv, D]: token i of row b lands at page
    ``table[b, (pos[b]+i) // page]``, offset ``(pos[b]+i) % page`` — one
    scatter, pure data movement again. The host guarantees distinct live
    rows write distinct pages (the copy-on-write discipline of
    serving/block_pool.py), so the scatter has no cross-row collisions;
    free rows' tables are all-zero, colliding harmlessly on the
    never-read scratch page.

    Multi-token windows past a row's extent are SAFE, not clamped: the
    speculative verify step (serving engines, ``speculative_k``) writes
    T = k+1 tokens per row, and a deep row's draft lanes can index past
    its table (paged) or past ``max_len`` (dense). XLA's default gather/
    dynamic_update_slice clamping would silently redirect those writes
    onto LIVE positions, so they are handled explicitly: paged lanes
    past the table redirect to the never-read scratch page (page 0),
    and dense per-row multi-token writes use a scatter with
    ``mode="drop"`` so out-of-range lanes write nothing. The host only
    ever commits tokens whose positions were in range, so dropped lanes
    are always rejected-draft garbage."""
    new = new.astype(cache_layer.dtype)
    if block_tables is not None:
        page = cache_layer.shape[1]
        b, t = new.shape[:2]
        n_pages = block_tables.shape[1]
        gpos = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None]  # [B,T]
        pidx = gpos // page
        pids = jnp.take_along_axis(
            block_tables, jnp.minimum(pidx, n_pages - 1), axis=1
        )
        pids = jnp.where(pidx < n_pages, pids, 0)  # OOB -> scratch page
        return cache_layer.at[pids, gpos % page].set(new)
    if getattr(pos, "ndim", 0):
        if new.shape[1] > 1:
            # Per-row MULTI-token write (the dense speculative verify
            # window): scatter with mode="drop" — a lane past max_len is
            # dropped instead of dynamic_update_slice's clamp-shift,
            # which would slide the whole window onto committed rows.
            b, t = new.shape[:2]
            gpos = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
            rows = jax.lax.broadcasted_iota(jnp.int32, (b, t), 0)
            return cache_layer.at[rows, gpos].set(new, mode="drop")
        return jax.vmap(
            lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (p, 0, 0))
        )(cache_layer, new, pos)
    return jax.lax.dynamic_update_slice(cache_layer, new, (0, pos, 0, 0))


def _write_kv(kv, k_new, v_new, pos, block_tables=None, kv_quant="none"):
    """Insert this step's [B, T, Hkv, D] K/V into the per-layer cache
    dict. ``kv_quant="int8"`` (paged only) QUANTIZES ON APPEND: the new
    tokens' values are rounded to int8 with per-token/per-head scales
    (ops/quant.quantize_kv — one f32->int8 convert each for K and V, the
    audit-counted quantize sites) and the value + scale pools are
    scattered through the same page indirection; already-written
    positions are never touched, so appending can never re-quantize a
    neighbour (the per-token-scale soundness argument)."""
    if kv_quant == "int8":
        from pytorch_distributed_tpu.ops.quant import quantize_kv

        kq, ks = quantize_kv(k_new)
        vq, vs = quantize_kv(v_new)
        return {
            "k": _write(kv["k"], kq, pos, block_tables),
            "v": _write(kv["v"], vq, pos, block_tables),
            "k_scale": _write(kv["k_scale"], ks, pos, block_tables),
            "v_scale": _write(kv["v_scale"], vs, pos, block_tables),
        }
    return {
        "k": _write(kv["k"], k_new, pos, block_tables),
        "v": _write(kv["v"], v_new, pos, block_tables),
    }


def lora_delta(x, lp, rows):
    """Per-row low-rank delta: the multi-tenant LoRA term
    (serving/adapters.py). ``x`` [B, T, Din] is the projection's input;
    ``lp`` is one layer's adapter slice — {"a": [slots, Din, r],
    "b": [slots, r, *out]} with slot 0 the zero adapter; ``rows`` [B]
    int32 picks each row's tenant slot. Returns [B, T, *out]:

        delta[b] = (x[b] @ a[rows[b]]) @ b[rows[b]]

    Nothing cross-row (tenant isolation is structural: row b's output
    can only read slot rows[b]) and nothing collective (under TP the
    caller routes the delta through the projection's EXISTING psum —
    ops/layers.dense ``extra_pre_reduce`` / the pre-``tp_reduce`` add —
    so the pinned Megatron all-reduce counts are untouched). A slot-0
    row's delta is exactly 0.0, and adding exact zeros is exact: no-
    tenant rows stay bit-equal the adapter-less engine.

    Lowering: two PLAIN 2D matmuls against ALL slots (batch flattened
    into the M dimension, the slot axis into N) with exact
    ``take_along_axis`` slot selection — NOT gather-then-batched-einsum.
    A B-batched GEMM's per-lane summation order varies with the batch
    shape on XLA:CPU, and the engines dispatch the same row under
    DIFFERENT batch shapes (prefill group sizes depend on queue churn);
    the flattened form keeps each output element a fixed-order dot over
    the contracting dim — the same shape family as the base
    projections, whose cross-group bit-stability the serving pins have
    relied on since PR 5. Cost: the rank-r GEMMs widen by the slot
    count — noise next to the base D x D projections."""
    a = lp["a"]  # [S, Din, r]
    bm = lp["b"]  # [S, r, *out]
    s_n, din, r = a.shape
    bsz, t = x.shape[:2]
    sel = rows[:, None, None, None]
    xf = x.reshape(bsz * t, din).astype(a.dtype)
    h_all = (xf @ a.transpose(1, 0, 2).reshape(din, s_n * r)).reshape(
        bsz, t, s_n, r
    )
    h = jnp.take_along_axis(h_all, sel, axis=2)  # [B, T, 1, r]
    bmat = bm.reshape(s_n, r, -1)  # [S, r, out]
    out = bmat.shape[-1]
    d_all = (
        h.reshape(bsz * t, r) @ bmat.transpose(1, 0, 2).reshape(r, s_n * out)
    ).reshape(bsz, t, s_n, out)
    d = jnp.take_along_axis(d_all, sel, axis=2)[:, :, 0]
    return d.reshape(x.shape[:2] + bm.shape[2:])


def _moe_mlp(m, mlp_params, cfg, act, tensor_axis=None):
    """Routed MLP for decode: top-1/top-k routing is per-token and
    cache-free, so only the MLP call differs from training. Capacity is
    set to the no-drop bound (cap = k * tokens): a dropped token at
    inference would silently zero its MLP contribution, and at decode
    shapes the slack is negligible. ``tensor_axis``: Megatron TP inside
    each expert (the training EP x TP placement, ops/moe._expert_compute)
    — routing runs on replicated activations so it agrees across shards,
    and the in-expert tp_reduce restores the full output."""
    from pytorch_distributed_tpu.ops.moe import moe_mlp

    out, _ = moe_mlp(
        m,
        mlp_params,
        activation=act,
        capacity_factor=float(cfg.n_experts),
        top_k=cfg.moe_top_k,
        dispatch_impl=cfg.moe_dispatch,
        tensor_axis=tensor_axis,
    )
    return out


def _gpt2_block(x, bp, kv, pos, cfg, tensor_axis=None,
                block_tables=None, paged_impl="gather", kv_quant="none",
                lora=None, lora_rows=None):
    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    a = layer_norm(x, bp["ln_1"], eps=eps)
    qkv = dense(a, bp["attn"]["c_attn"])  # [B, T, 3, H(/tp), D]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if lora is not None:
        # Query-only on the fused projection: K/V stay tenant-agnostic
        # so cached pages keep their pure-function-of-tokens soundness
        # (serving/adapters.py).
        q = q + lora_delta(a, lora["q"], lora_rows).astype(q.dtype)
    kv = _write_kv(kv, k, v, pos, block_tables, kv_quant)
    a = _cached_attention(
        q, kv, pos, block_tables, paged_impl, kv_quant
    ).reshape(b, t, -1)
    proj_extra = (
        lora_delta(a, lora["c_proj"], lora_rows)
        if lora is not None else None
    )
    x = x + dense(
        a, bp["attn"]["c_proj"], tp_reduce_axis=tensor_axis,
        extra_pre_reduce=proj_extra,
    )
    m = layer_norm(x, bp["ln_2"], eps=eps)
    act = activation(cfg.activation_function)
    if cfg.n_experts:
        m = _moe_mlp(m, bp["mlp"], cfg, act, tensor_axis)
        return x + m, kv
    m = act(dense(m, bp["mlp"]["c_fc"]))
    return x + dense(m, bp["mlp"]["c_proj"], tp_reduce_axis=tensor_axis), kv


def _llama_block(x, bp, kv, pos, cfg, cos, sin, tensor_axis=None,
                 block_tables=None, paged_impl="gather", kv_quant="none",
                 lora=None, lora_rows=None):
    from pytorch_distributed_tpu.ops.quant import qdot
    from pytorch_distributed_tpu.ops.tp import tp_reduce

    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]
    d = cfg.head_dim
    a = rms_norm(x, bp["ln_attn"], eps=eps)
    # qdot == `a @ w.astype(a.dtype)` for plain weights (bit-identical
    # dot_general) and the int8 weight-only matmul for quantized ones.
    q_pre = qdot(a, bp["attn"]["wq"])
    if lora is not None:
        # wq (column-parallel) + wo (row-parallel, delta joins the
        # partial BEFORE the psum); wk/wv deliberately untouched so
        # cached K/V stays tenant-agnostic (serving/adapters.py).
        q_pre = q_pre + lora_delta(a, lora["wq"], lora_rows).astype(
            q_pre.dtype
        )
    q = apply_rope(q_pre.reshape(b, t, -1, d), cos, sin)
    k = apply_rope(qdot(a, bp["attn"]["wk"]).reshape(b, t, -1, d), cos, sin)
    v = qdot(a, bp["attn"]["wv"]).reshape(b, t, -1, d)
    kv = _write_kv(kv, k, v, pos, block_tables, kv_quant)
    a = _cached_attention(
        q, kv, pos, block_tables, paged_impl, kv_quant
    ).reshape(b, t, -1)
    wo_out = qdot(a, bp["attn"]["wo"])
    if lora is not None:
        wo_out = wo_out + lora_delta(a, lora["wo"], lora_rows).astype(
            wo_out.dtype
        )
    x = x + tp_reduce(wo_out, tensor_axis)
    m = rms_norm(x, bp["ln_mlp"], eps=eps)
    if cfg.n_experts:
        return x + _moe_mlp(m, bp["mlp"], cfg, jax.nn.silu, tensor_axis), kv
    gate = jax.nn.silu(qdot(m, bp["mlp"]["gate"]))
    up = qdot(m, bp["mlp"]["up"])
    down = qdot(gate * up, bp["mlp"]["down"])
    return x + tp_reduce(down, tensor_axis), kv


def forward(
    params: Params,
    input_ids: jax.Array,  # [B, T] — full prompt (prefill) or one token
    cfg: ModelConfig,
    cache: Cache,
    pos: jax.Array | int,  # tokens already in the cache (scalar or [B])
    *,
    tensor_axis: str | None = None,
    block_transform=None,
    prefetch_buffers: int = 0,
    block_tables: jax.Array | None = None,
    paged_impl: str = "gather",
    kv_quant: str = "none",
    lora: tuple | None = None,
) -> tuple[jax.Array, Cache]:
    """Run T tokens at positions pos..pos+T-1. Returns ([B, T, V] logits,
    updated cache). MoE configs route each token through the expert MLPs
    (no-drop capacity — see ``_moe_mlp``); routing is stateless, so the
    KV cache is untouched by the choice of MLP.

    ``block_tables`` [B, n_pages] switches the cache to the PAGED pool
    layout (``init_paged_cache``: [L, P, page, Hkv, D] leaves) with
    per-row page indirection — the serving block-pool mode
    (serving/engine.PagedBatchedDecodeEngine). ``pos`` must then be a
    [B] vector. ``paged_impl`` picks the paged attention backend for
    single-token steps ("gather" XLA fallback / "kernel" Pallas /
    "kernel_interpret" for the CPU rig's kernel tests).

    ``pos`` may be a [B] VECTOR: each batch row then runs at its own
    position (cache write offset, attention mask, wpe/rope angles) — the
    slot-batched decode mode (serving/engine.BatchedDecodeEngine), where
    independent requests occupy rows of one program at unrelated depths.
    Row b's computation is bit-identical to the scalar-pos call at
    pos[b] with that row alone (pure per-row data movement + the same
    per-row reductions).

    ``tensor_axis``: set when called inside shard_map with block params
    sharded Megatron-style (tensor-parallel decode): attention runs on
    the LOCAL heads against a local-head cache shard, row-parallel
    projections psum over the axis, and the logits come back replicated.

    ``block_transform`` / ``prefetch_buffers``: the scan-over-layers hooks
    (ops/layer_scan.py) — ZeRO-3 decode passes a gather/replicate
    transform per layer, and with ``prefetch_buffers`` > 0 a whole
    window's gathers are issued before its first block computes, so layer
    l+1's shards stream in under layer l's compute (serving/engine.py).
    Bit-equivalent to the default per-layer schedule for any window size.

    ``lora``: ``(stacked adapter tree, [B] tenant-slot rows)`` — the
    multi-tenant low-rank deltas (serving/adapters.py). The tree's
    leaves are [L, slots, ...] and scan alongside the blocks; each
    row's delta is applied per-row inside the blocks
    (``lora_delta``) with slot 0 the exact-zero adapter. Incompatible
    with ``block_transform`` (the ZeRO-3 gather hook transforms the
    whole sliced tree — adapters are plain operands, not sharded
    params), rejected loudly.
    """
    b, t = input_ids.shape
    dtype = jnp.dtype(cfg.dtype)
    pos = jnp.asarray(pos, jnp.int32)
    per_row = pos.ndim > 0  # [B] vector: slot-batched, per-row positions
    if block_tables is not None and not per_row:
        raise ValueError(
            "paged decode (block_tables) requires a per-row [B] pos "
            "vector — every paged row owns its own position"
        )
    if kv_quant not in ("none", "int8"):
        raise ValueError(
            f"kv_quant must be 'none' or 'int8', got {kv_quant!r}"
        )
    if kv_quant != "none" and block_tables is None:
        raise ValueError(
            "kv_quant requires the paged cache layout (block_tables): "
            "dense caches stay full precision — quantized pages are the "
            "block-pool feature (init_paged_cache(kv_quant=...))"
        )
    lora_tree = lora_rows = None
    if lora is not None:
        if block_transform is not None:
            raise ValueError(
                "lora adapters are incompatible with block_transform "
                "(ZeRO-3 decode): the gather hook transforms the whole "
                "sliced layer tree, and the stacked adapter operands are "
                "plain per-dispatch values, not sharded params — serve "
                "adapters from plain or tensor-only meshes"
            )
        lora_tree, lora_rows = lora
        lora_rows = jnp.asarray(lora_rows, jnp.int32)

    if cfg.family == "gpt2":
        if per_row:
            rows = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
            wpe = params["wpe"][rows]  # [B, T, E], row b at its own pos
        else:
            wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos, t, axis=0)
        x = (params["wte"][input_ids] + wpe).astype(dtype)
        block = partial(
            _gpt2_block, cfg=cfg, tensor_axis=tensor_axis,
            block_tables=block_tables, paged_impl=paged_impl,
            kv_quant=kv_quant,
        )
    elif cfg.family == "llama":
        x = params["wte"][input_ids].astype(dtype)
        cos, sin = rope_angles(
            t, cfg.head_dim, cfg.rope_theta,
            offset=pos[:, None] if per_row else pos,
        )
        block = partial(
            _llama_block, cfg=cfg, cos=cos, sin=sin,
            tensor_axis=tensor_axis,
            block_tables=block_tables, paged_impl=paged_impl,
            kv_quant=kv_quant,
        )
    else:
        raise KeyError(f"unknown model family {cfg.family!r}")

    def block_body(x, bp, extra):
        # ``extra["kv"]`` is one layer's cache-leaf dict (k/v, plus the
        # scale pools when quantized) — scan_layers slices/stacks the
        # whole dict, so the leaf set is the cache layout's business,
        # not the scan's. ``extra["lora"]`` (when adapters ride the
        # dispatch) is that layer's [slots, ...] adapter slice; the
        # [B] rows vector is layer-invariant and closes over the scan.
        kv_l = extra["kv"]
        if lora_tree is not None:
            return block(
                x, bp, kv_l, pos,
                lora=extra["lora"], lora_rows=lora_rows,
            )
        return block(x, bp, kv_l, pos)

    extras = {"kv": cache}
    if lora_tree is not None:
        extras["lora"] = lora_tree
    x, kv = scan_layers(
        block_body,
        x,
        params["blocks"],
        extras=extras,
        remat_mode="none",
        block_transform=block_transform,
        prefetch_buffers=prefetch_buffers,
        collect_ys=True,
    )

    from pytorch_distributed_tpu.models import get_model

    logits = get_model(cfg).head(params, x, cfg)
    return logits, kv


# -- sampling --------------------------------------------------------------
#
# Greedy-vs-sampled is the ONE static bit (a greedy program has no
# division, no vocab sort, no PRNG); everything else about the sampling
# config is a traced scalar, so a serving loop sweeping temperature /
# top_k / top_p reuses one compiled program. ``None`` top_k / top_p are
# encoded as out-of-range sentinels (k = vocab size keeps the full
# support; p = 2.0 keeps every cumulative mass) rather than separate
# static program variants.


def sampling_scalars(
    temperature, top_k, top_p, vocab_size: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Encode the (possibly-None) Python sampling config as the traced
    scalar triple every sampled program takes. Explicit dtypes — a
    weak-typed Python scalar would retrace when its Python type changes
    (the exact hazard analysis/jaxpr_scan flags). ``top_k`` in
    {None, 0} means top-k disabled (full support — the HF convention for
    0; a traced k=0 would otherwise mask EVERY token and silently
    degrade to greedy); negative k is rejected here, where the Python
    int is still visible."""
    if top_k is not None and top_k < 0:
        raise ValueError(f"top_k must be >= 0 or None, got {top_k}")
    t = jnp.asarray(temperature if temperature else 1.0, jnp.float32)
    k = jnp.asarray(top_k or vocab_size, jnp.int32)
    p = jnp.asarray(2.0 if top_p is None else top_p, jnp.float32)
    return t, k, p


def _sample_greedy(logits):
    """[B, V] -> [B] argmax tokens (the static greedy program)."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _sample_traced(logits, temperature, key, top_k, top_p):
    """[B, V] -> [B] next tokens with TRACED temperature/top_k/top_p
    (see ``sampling_scalars`` for the None-sentinels). top_k restricts
    sampling to the k highest-probability tokens; top_p (nucleus)
    restricts it to the smallest set whose probability mass reaches p.
    Given BOTH, top-k applies first and the nucleus is taken within it
    (HF semantics: the renormalised mass is over the top-k support).

    Mechanics: one full-vocab descending sort per step (``lax.top_k`` at
    k = V — the price of a traced k; HF's sampler pays the same sort for
    top_p), then rank/cumulative-mass masks. The argmax token always
    survives both filters, so top_k=1 or top_p->0 reduce to greedy.
    """
    logits = logits.astype(jnp.float32) / temperature
    v = logits.shape[-1]
    vals, idx = jax.lax.top_k(logits, v)  # [B, V], sorted desc
    rank = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 1)
    in_k = rank < top_k
    probs = jax.nn.softmax(jnp.where(in_k, vals, -jnp.inf), axis=-1)
    # Keep tokens whose CUMULATIVE mass (within the top-k support)
    # before them is < p — the argmax token always survives.
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    vals = jnp.where(in_k & (cum_before < top_p), vals, -jnp.inf)
    choice = jax.random.categorical(key, vals, axis=-1)  # [B]
    return jnp.take_along_axis(
        idx, choice[:, None], axis=-1
    )[:, 0].astype(jnp.int32)


def sample_token(logits, sampled: bool, temperature, key, top_k, top_p):
    """One next-token draw: ``sampled`` is the static greedy/sampled bit,
    the rest are traced. Shared by the monolithic paths and the serving
    engine so the two can never drift (their bit-equivalence is pinned in
    tests/test_serving.py)."""
    if not sampled:
        return _sample_greedy(logits)
    return _sample_traced(logits, temperature, key, top_k, top_p)


def sample_token_rows(logits, greedy, temperature, keys, top_k, top_p):
    """One next-token draw PER ROW with fully per-row sampling state:
    ``logits`` [B, V]; ``greedy`` [B] bool plus ``temperature``/``top_k``/
    ``top_p`` [B] — all TRACED, so a slot batch can mix greedy and sampled
    requests with any configs in one compiled program; ``keys`` [B] typed
    PRNG keys (one per request, already folded to the row's step).

    Row r's draw is bit-identical to the serial path's
    ``sample_token(logits[r:r+1], ...)`` with the same key: the sampled
    branch IS the B=1 ``_sample_traced`` body vmapped over rows (vmap of
    threefry is elementwise in (key, counter), so the drawn bits match the
    individual calls), and greedy rows select the same argmax. Unlike the
    serial engine's static greedy/sampled split, greedy here is a traced
    flag — the batch must serve both kinds of row in one program, so the
    sort always runs and greedy rows discard the draw (the price of one
    program for every traffic mix)."""

    def row(l, g, t, key, k, p):
        drawn = _sample_traced(l[None], t, key, k, p)[0]
        return jnp.where(g, _sample_greedy(l[None])[0], drawn)

    return jax.vmap(row)(logits, greedy, temperature, keys, top_k, top_p)


def speculative_accept(
    drafts: jax.Array,      # [B, K] int32 draft tokens (lane-padded)
    verified: jax.Array,    # [B, K] int32 greedy next-tokens for lanes 0..K-1
    n_draft: jax.Array,     # [B] int32 valid draft count per row (0..K)
) -> jax.Array:
    """Per-row TRACED accept lengths for batched speculative decoding
    (serving/engine.py ``decode_spec_step``): draft lane j survives iff
    every earlier lane survived AND it matches the model's own greedy
    choice for that position AND the lane is valid (j < n_draft[b] —
    rows with fewer drafts than the program width ride padded lanes
    that can never be accepted). Returns [B] int32 in [0, K]; the
    committed tokens are then ``out[b, :n_acc[b]+1]`` (accepted drafts
    plus the model's bonus/correction token) — the same acceptance rule
    as the serial prompt-lookup loop (models/speculative.py), so the
    greedy output is the plain decode by construction, whatever the
    drafts were. All rows share one compiled program: acceptance is
    data, not shape."""
    lanes = jax.lax.broadcasted_iota(jnp.int32, drafts.shape, 1)
    match = (drafts == verified) & (lanes < n_draft[:, None])
    return jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)


def _generate_impl(
    params, prompt, cfg, max_new_tokens, sampled, temperature, key,
    max_len, top_k, top_p, tensor_axis=None, n_kv=None,
):
    """Shared monolithic generation body: prefill over the prompt, then a
    fori_loop of single-token decode steps against the cache. Runs plain
    (generate_monolithic) or inside shard_map (generate_tp_monolithic).
    ``sampled`` is static; temperature/top_k/top_p arrive traced."""
    b, tp = prompt.shape
    total = tp + max_new_tokens
    max_len = max_len or total
    # key is never None here: _check_sample_args owns the greedy-path
    # dummy-key substitution for every entry point.

    cache = init_cache(cfg, b, max_len, n_kv=n_kv)
    if tensor_axis is not None:
        # The cache carries tensor-sharded values (local-head K/V); its
        # zero init must be typed varying over the axis or the fori_loop
        # carry types mismatch under check_vma.
        from pytorch_distributed_tpu.ops.tp import pvary_missing

        cache = jax.tree.map(
            lambda c: pvary_missing(c, (tensor_axis,)), cache
        )
    logits, cache = forward(
        params, prompt, cfg, cache, 0, tensor_axis=tensor_axis
    )
    next_tok = sample_token(
        logits[:, -1], sampled, temperature, key, top_k, top_p
    )

    out = jnp.zeros((b, total), jnp.int32)
    out = jax.lax.dynamic_update_slice(out, prompt.astype(jnp.int32), (0, 0))
    out = out.at[:, tp].set(next_tok)

    def step(i, carry):
        out, cache, tok = carry
        pos = tp + i
        logits, cache = forward(
            params, tok[:, None], cfg, cache, pos, tensor_axis=tensor_axis
        )
        nxt = sample_token(
            logits[:, -1], sampled, temperature,
            jax.random.fold_in(key, i), top_k, top_p,
        )
        out = out.at[:, pos + 1].set(nxt)
        return out, cache, nxt

    out, _, _ = jax.lax.fori_loop(
        0, max_new_tokens - 1, step, (out, cache, next_tok)
    )
    return out


# repolint: allow(jit-donation-decision) — params are the serving
# weights, reused by every generate call; the cache is jit-internal on
# this legacy reference path (the serving engine is the donated-cache
# fast path).
@partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "max_len", "sampled"),
)
def _monolithic_jit(
    params, prompt, key, temperature, top_k, top_p,
    *, cfg, max_new_tokens, max_len, sampled,
):
    return _generate_impl(
        params, prompt, cfg, max_new_tokens, sampled, temperature, key,
        max_len, top_k, top_p,
    )


def generate_monolithic(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """The original single-program generation path: prefill + fori_loop of
    decode steps inside ONE jit. Returns [B, Tp + max_new_tokens].

    Kept as the reference the serving engine is pinned bit-equal against
    and as decode_bench's "per-call path" leg. Sampling params are traced
    (a config sweep reuses one compiled program — the compile key is only
    (shapes, cfg, max_new_tokens, max_len, greedy-vs-sampled)); the KV
    cache is jit-internal, re-allocated and re-zeroed every call — the
    cost ``serving.engine.DecodeEngine``'s donated cache pool removes.
    """
    key = _check_sample_args(
        prompt, max_new_tokens, temperature, key, max_len=max_len
    )
    t, k, p = sampling_scalars(temperature, top_k, top_p, cfg.vocab_size)
    return _monolithic_jit(
        params, prompt, key, t, k, p,
        cfg=cfg, max_new_tokens=max_new_tokens, max_len=max_len,
        sampled=temperature > 0,
    )


def generate(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Autoregressive generation: returns [B, Tp + max_new_tokens].

    Compat shim over ``serving.engine.DecodeEngine`` (exact-length
    buckets, so compilation behaviour matches the old monolithic entry):
    prefill + decode run as two long-lived compiled programs with the KV
    cache donated between them and pooled across calls. Bit-equal to
    ``generate_monolithic`` (pinned in tests/test_serving.py).
    """
    key = _check_sample_args(
        prompt, max_new_tokens, temperature, key, max_len=max_len
    )
    from pytorch_distributed_tpu.serving.engine import shim_engine

    engine = shim_engine(
        cfg, max_len or (prompt.shape[1] + max_new_tokens)
    )
    return engine.generate(
        params, prompt, max_new_tokens, temperature=temperature, key=key,
        top_k=top_k, top_p=top_p,
    )


def _validate_tp_mesh(cfg: ModelConfig, mesh_cfg) -> None:
    """Shared generate_tp entry validation (shim + monolithic)."""
    tp_size = mesh_cfg.tensor
    if tp_size <= 1:
        raise ValueError("generate_tp needs mesh_cfg.tensor > 1")
    for ax in ("data", "fsdp", "seq", "pipe", "expert"):
        if getattr(mesh_cfg, ax) > 1:
            raise NotImplementedError(
                f"generate_tp supports a tensor-only mesh (got {ax}="
                f"{getattr(mesh_cfg, ax)})"
            )
    if cfg.n_experts and cfg.inner_dim % tp_size:
        raise ValueError(
            f"tensor={tp_size} must divide the MoE expert hidden dim "
            f"inner_dim={cfg.inner_dim} (experts run Megatron TP on F)"
        )
    if cfg.n_head % tp_size or cfg.kv_heads % tp_size:
        raise ValueError(
            f"tensor={tp_size} must divide n_head={cfg.n_head} and "
            f"kv_heads={cfg.kv_heads}"
        )


def _validate_fsdp_mesh(mesh_cfg) -> None:
    """Shared generate_fsdp entry validation (shim + monolithic)."""
    if mesh_cfg.fsdp <= 1:
        raise ValueError("generate_fsdp needs mesh_cfg.fsdp > 1")
    for ax in ("data", "tensor", "seq", "pipe", "expert"):
        if getattr(mesh_cfg, ax) > 1:
            raise NotImplementedError(
                f"generate_fsdp supports an fsdp-only mesh (got {ax}="
                f"{getattr(mesh_cfg, ax)}); combine with generate_tp's "
                "tensor sharding is future surface"
            )
    if mesh_cfg.strategy != "full_shard":
        raise ValueError(
            "generate_fsdp decodes from full_shard (ZeRO-3) param "
            f"layouts; strategy={mesh_cfg.strategy!r} keeps params "
            "replicated — plain generate already covers it"
        )


def generate_tp(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    mesh_cfg,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Tensor-parallel generation over a "tensor" mesh (meshed decode —
    models whose weights exceed one chip sample across tp shards).

    Block params shard Megatron-style per parallel/sharding.py's rule
    table (the SAME layout training leaves them in, so a trained sharded
    state decodes with no resharding); each shard runs attention on its
    LOCAL heads against a local-head KV cache (1/tp of the cache HBM),
    row-parallel projections psum over the axis, and the replicated
    logits sample identically on every shard. Compat shim over the TP
    ``DecodeEngine``; ``generate_tp_monolithic`` is the one-jit reference.
    """
    _validate_tp_mesh(cfg, mesh_cfg)
    key = _check_sample_args(
        prompt, max_new_tokens, temperature, key, max_len=max_len
    )
    from pytorch_distributed_tpu.serving.engine import shim_engine

    engine = shim_engine(
        cfg, max_len or (prompt.shape[1] + max_new_tokens),
        mesh_cfg=mesh_cfg,
    )
    return engine.generate(
        params, prompt, max_new_tokens, temperature=temperature, key=key,
        top_k=top_k, top_p=top_p,
    )


def generate_tp_monolithic(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    mesh_cfg,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """One-jit TP generation (the pre-engine reference path)."""
    _validate_tp_mesh(cfg, mesh_cfg)
    key = _check_sample_args(
        prompt, max_new_tokens, temperature, key, max_len=max_len
    )

    fn, shardings = _tp_generate_compiled(
        cfg, mesh_cfg, max_new_tokens, max_len, temperature > 0
    )
    t, k, p = sampling_scalars(temperature, top_k, top_p, cfg.vocab_size)
    # device_put with the target shardings is a no-op when params are
    # already placed, so repeat calls only pay the (cached) jit lookup.
    return fn(jax.device_put(params, shardings), prompt, key, t, k, p)


def nonfinite_rows(logits: jax.Array) -> jax.Array:
    """[B, V] (or [B, T, V]) -> [B] bool: True where ANY logit in the row
    is NaN/Inf — the cheap traced fault sentinel every serving program
    returns next to its sampled token (serving/engine.py). Reduces over
    every axis but the batch axis; elementwise + one reduction, so it adds
    no collectives to any program (the audit registry pins the budgets)
    and costs nothing against the decode step's matmuls."""
    axes = tuple(range(1, logits.ndim))
    return jnp.any(~jnp.isfinite(logits), axis=axes)


def _check_sample_args(prompt, max_new_tokens, temperature, key,
                       max_len=None):
    """Shared generate-entry validation; returns the PRNG key (greedy
    paths get a dummy, unused by sampling). Rejects loudly, naming the
    limit, instead of failing late in a compiled program:

    - empty prompts (the first token would sample from a pad position);
    - ``max_new_tokens <= 0`` (a generate that generates nothing is a
      caller bug — the old 0-token early-return silently returned the
      prompt, which hid budget-accounting mistakes in serving loops);
    - ``prompt + max_new_tokens > max_len`` when the cache capacity is
      known (the KV write past ``max_len`` would otherwise fail deep in
      dispatch or silently clamp);
    - temperature sampling without a key.
    """
    tp = prompt.shape[-1]
    if tp == 0:
        raise ValueError(
            "empty prompt: need at least one token to prefill (an empty "
            "prompt would sample the first token from a pad position)"
        )
    if max_new_tokens <= 0:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens} — a "
            "request that generates nothing is a no-op; don't dispatch it"
        )
    if max_len is not None and tp + max_new_tokens > max_len:
        raise ValueError(
            f"prompt ({tp}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_len {max_len}: the KV cache holds max_len positions, so "
            "the request cannot fit — shorten it or raise max_len"
        )
    if temperature > 0.0 and key is None:
        raise ValueError("temperature sampling requires a PRNG key")
    if key is None:
        key = jax.random.key(0)
    return key


def _mesh_param_shardings(cfg, mesh_cfg):
    """(mesh, partition-spec tree, NamedSharding tree) for decode params
    under ``mesh_cfg`` — shared by the meshed decode paths so spec
    derivation cannot diverge between them. Specs come from the abstract
    init, so no concrete params are needed (lru_cache-friendly)."""
    from jax.sharding import NamedSharding

    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_tpu.parallel.sharding import (
        param_partition_specs,
    )

    mesh = make_mesh(mesh_cfg)
    abstract = jax.eval_shape(
        lambda k: get_model(cfg).init(k, cfg), jax.random.key(0)
    )
    p_specs = param_partition_specs(abstract, mesh_cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        p_specs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )
    return mesh, p_specs, shardings


def generate_fsdp(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    mesh_cfg,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """Decode from ZeRO-3-sharded params over an "fsdp" mesh — sample IN
    PLACE from the layout full-shard training leaves the weights in (no
    resharding, and per-chip param HBM stays 1/fsdp of the model).

    Compat shim over the ZeRO-3 ``DecodeEngine``: the auto-partitioned
    decode with each scanned layer's shards gathered per layer — and,
    with ``mesh_cfg.prefetch_buffers`` > 0, gathered a WINDOW at a time
    so layer l+1's all-gather streams under layer l's compute (the same
    ops/layer_scan schedule training's explicit ZeRO-3 path uses; closes
    ROADMAP PR-3 follow-up (c)). ``generate_fsdp_monolithic`` is the
    one-jit reference. MoE configs work unchanged (routing and dispatch
    are ordinary auto-sharded ops here).
    """
    _validate_fsdp_mesh(mesh_cfg)
    key = _check_sample_args(
        prompt, max_new_tokens, temperature, key, max_len=max_len
    )
    from pytorch_distributed_tpu.serving.engine import shim_engine

    engine = shim_engine(
        cfg, max_len or (prompt.shape[1] + max_new_tokens),
        mesh_cfg=mesh_cfg,
    )
    return engine.generate(
        params, prompt, max_new_tokens, temperature=temperature, key=key,
        top_k=top_k, top_p=top_p,
    )


def generate_fsdp_monolithic(
    params: Params,
    prompt: jax.Array,  # [B, Tp] int
    cfg: ModelConfig,
    mesh_cfg,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    max_len: int | None = None,
    top_k: int | None = None,
    top_p: float | None = None,
) -> jax.Array:
    """One-jit ZeRO-3 generation (the pre-engine reference path): the
    decode loop is jitted with params carrying their full_shard
    NamedShardings and XLA's SPMD partitioner inserts the just-in-time
    per-layer gathers (the stacked [L, ...] block leaves shard a WEIGHT
    dim, never L — parallel/sharding.py)."""
    _validate_fsdp_mesh(mesh_cfg)
    key = _check_sample_args(
        prompt, max_new_tokens, temperature, key, max_len=max_len
    )

    fn, shardings = _fsdp_generate_compiled(
        cfg, mesh_cfg, max_new_tokens, max_len, temperature > 0
    )
    t, k, p = sampling_scalars(temperature, top_k, top_p, cfg.vocab_size)
    return fn(jax.device_put(params, shardings), prompt, key, t, k, p)


@functools.lru_cache(maxsize=None)
def _fsdp_generate_compiled(cfg, mesh_cfg, max_new_tokens, max_len, sampled):
    """(jitted auto-path generate fn, full_shard param shardings) for one
    static config — cached like _tp_generate_compiled. Sampling params
    are call-time traced operands, so they are NOT part of this key."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, _, shardings = _mesh_param_shardings(cfg, mesh_cfg)
    replicated = NamedSharding(mesh, P())

    def body(params, prompt, key, temperature, top_k, top_p):
        return _generate_impl(
            params, prompt, cfg, max_new_tokens, sampled, temperature, key,
            max_len, top_k, top_p,
        )

    # repolint: allow(jit-donation-decision) — sharded serving weights
    # are reused across generate_fsdp calls; nothing here is consumed.
    fn = jax.jit(
        body,
        in_shardings=(shardings,) + (replicated,) * 5,
        out_shardings=replicated,
    )
    return fn, shardings


@functools.lru_cache(maxsize=None)
def _tp_generate_compiled(cfg, mesh_cfg, max_new_tokens, max_len, sampled):
    """(jitted shard_map generate fn, param shardings) for one static
    config — cached so a serving loop does not retrace/recompile the
    whole prefill+fori_loop program per generate_tp call (both config
    dataclasses are frozen, hence hashable; traced sampling params are
    NOT part of the key). Param specs are derived from the abstract init
    so the cache needs no concrete params."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.utils.compat import shard_map

    mesh, p_specs, shardings = _mesh_param_shardings(cfg, mesh_cfg)

    def body(params, prompt, key, temperature, top_k, top_p):
        return _generate_impl(
            params, prompt, cfg, max_new_tokens, sampled, temperature, key,
            max_len, top_k, top_p,
            tensor_axis="tensor", n_kv=cfg.kv_heads // mesh_cfg.tensor,
        )

    smapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(p_specs, P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=True,
    )
    # repolint: allow(jit-donation-decision) — TP serving weights are
    # reused across generate_tp calls; the KV cache is jit-internal on
    # this reference path.
    return jax.jit(smapped), shardings
