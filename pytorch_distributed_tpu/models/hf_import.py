"""GPT-2 weight import/export: HF checkpoints and reference-style state dicts.

Capability twin of reference model/my_gpt2.py:250-312:
- ``save()``/``from_pretrained()`` — our framework-native equivalent is
  train/checkpoint.py; this module covers the *interchange* formats;
- ``from_hf_pretrained()`` with the Conv1D->Linear transpose
  (``_convert_conv1d_to_linear_state_dict``, reference :254-280).

Layout notes (why the transposes differ from the reference):
- HF GPT-2 stores c_attn/c_proj/c_fc as Conv1D with weight [in, out].
- torch nn.Linear stores [out, in] — hence the reference transposes.
- Our dense kernels are [in, out] (ops/layers.py), so HF Conv1D weights
  import WITHOUT transpose; torch-Linear-style dicts (produced by the
  reference's ``save()``) need the transpose instead.

Both importers accept a flat ``{name: array}`` mapping (torch tensors or
numpy arrays; anything with ``numpy()`` or ``__array__``) so torch is an
optional dependency. Stacking: per-layer HF arrays ``h.{i}.*`` are stacked
along a new leading layer axis to match our scanned [L, ...] params.
"""

from __future__ import annotations

import numpy as np

from pytorch_distributed_tpu.config import ModelConfig


def _to_np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().numpy()
    return np.asarray(x)


# HF GPT-2 parameter names (relative prefix; both bare and "transformer."-
# prefixed checkpoints exist in the wild).
_HF_BLOCK_KEYS = {
    "ln_1.weight": ("ln_1", "scale"),
    "ln_1.bias": ("ln_1", "bias"),
    "attn.c_attn.weight": ("attn", "c_attn", "kernel"),
    "attn.c_attn.bias": ("attn", "c_attn", "bias"),
    "attn.c_proj.weight": ("attn", "c_proj", "kernel"),
    "attn.c_proj.bias": ("attn", "c_proj", "bias"),
    "ln_2.weight": ("ln_2", "scale"),
    "ln_2.bias": ("ln_2", "bias"),
    "mlp.c_fc.weight": ("mlp", "c_fc", "kernel"),
    "mlp.c_fc.bias": ("mlp", "c_fc", "bias"),
    "mlp.c_proj.weight": ("mlp", "c_proj", "kernel"),
    "mlp.c_proj.bias": ("mlp", "c_proj", "bias"),
}

# Every one of these is an HF Conv1D [in, out] kernel — our dense kernels use
# the same [in, out] layout, so they import transpose-free (unlike the
# reference, which transposes for nn.Linear, my_gpt2.py:254-280).
_KERNELS = {
    "attn.c_attn.weight",
    "attn.c_proj.weight",
    "mlp.c_fc.weight",
    "mlp.c_proj.weight",
}


def _strip_prefix(sd: dict) -> dict:
    out = {}
    for k, v in sd.items():
        if k.startswith("transformer."):
            k = k[len("transformer.") :]
        out[k] = v
    return out


def _set_nested(tree: dict, path: tuple[str, ...], value) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def from_hf_gpt2_state_dict(sd: dict, cfg: ModelConfig) -> dict:
    """Convert an HF GPT2LMHeadModel/GPT2Model state dict to our params.

    HF Conv1D weights are [in, out] — identical to our kernel layout, so no
    transpose is needed (the reference's transpose exists only because torch
    Linear is [out, in], reference my_gpt2.py:254-280). ``lm_head.weight`` is
    ignored: the head is tied to wte (reference :206).
    """
    return _import_state_dict(sd, cfg, kernels_transposed=False)


def from_reference_state_dict(sd: dict, cfg: ModelConfig) -> dict:
    """Convert a torch-Linear-layout state dict (what the reference model's
    ``save()`` produces after its Conv1D->Linear conversion) to our params:
    every linear weight is [out, in] and IS transposed here."""
    return _import_state_dict(sd, cfg, kernels_transposed=True)


def _import_state_dict(
    sd: dict, cfg: ModelConfig, *, kernels_transposed: bool
) -> dict:
    sd = _strip_prefix({k: _to_np(v) for k, v in sd.items()})
    dtype = np.dtype(cfg.param_dtype)

    def kernel_fix(name: str, arr: np.ndarray) -> np.ndarray:
        if name in _KERNELS and kernels_transposed:
            return arr.T
        return arr

    params: dict = {
        "wte": sd["wte.weight"].astype(dtype),
        "wpe": sd["wpe.weight"].astype(dtype),
        "ln_f": {
            "scale": sd["ln_f.weight"].astype(dtype),
            "bias": sd["ln_f.bias"].astype(dtype),
        },
        "blocks": {},
    }
    if params["wte"].shape != (cfg.vocab_size, cfg.n_embd):
        raise ValueError(
            f"wte shape {params['wte'].shape} != "
            f"({cfg.vocab_size}, {cfg.n_embd})"
        )

    for hf_key, path in _HF_BLOCK_KEYS.items():
        per_layer = []
        for layer in range(cfg.n_layer):
            name = f"h.{layer}.{hf_key}"
            if name not in sd:
                raise KeyError(f"missing {name!r} in state dict")
            per_layer.append(kernel_fix(hf_key, sd[name]))
        stacked = np.stack(per_layer).astype(dtype)
        _set_nested(params["blocks"], path, stacked)

    expect_qkv = (cfg.n_layer, cfg.n_embd, 3 * cfg.n_embd)
    got = params["blocks"]["attn"]["c_attn"]["kernel"].shape
    if got != expect_qkv:
        raise ValueError(
            f"c_attn kernel stacked shape {got} != {expect_qkv} — wrong "
            "layout? (use from_reference_state_dict for torch-Linear dicts)"
        )
    # HF's flat [E, 3E] merged-QKV columns are [q(E) | k(E) | v(E)] with each
    # E block laid out head-major — exactly our [E, 3, H, D] kernel flattened,
    # so the reshape is a view, no permutation (models/gpt2.py layout note).
    h, d = cfg.n_head, cfg.head_dim
    attn = params["blocks"]["attn"]["c_attn"]
    attn["kernel"] = attn["kernel"].reshape(cfg.n_layer, cfg.n_embd, 3, h, d)
    attn["bias"] = attn["bias"].reshape(cfg.n_layer, 3, h, d)
    return params


def to_hf_gpt2_state_dict(params: dict) -> dict:
    """Export our params to HF GPT-2 (Conv1D-layout) naming — the inverse of
    ``from_hf_gpt2_state_dict``; includes the tied ``lm_head.weight``."""
    out = {
        "wte.weight": np.asarray(params["wte"]),
        "wpe.weight": np.asarray(params["wpe"]),
        "ln_f.weight": np.asarray(params["ln_f"]["scale"]),
        "ln_f.bias": np.asarray(params["ln_f"]["bias"]),
        "lm_head.weight": np.asarray(params["wte"]),
    }
    blocks = params["blocks"]
    n_layer = np.asarray(blocks["ln_1"]["scale"]).shape[0]

    def get(path):
        node = blocks
        for p in path:
            node = node[p]
        return np.asarray(node)

    for hf_key, path in _HF_BLOCK_KEYS.items():
        stacked = get(path)
        if path[-2:] == ("c_attn", "kernel"):
            # [L, E, 3, H, D] -> HF's flat [L, E, 3E] (inverse of import).
            stacked = stacked.reshape(stacked.shape[0], stacked.shape[1], -1)
        elif path[-2:] == ("c_attn", "bias"):
            stacked = stacked.reshape(stacked.shape[0], -1)
        for layer in range(n_layer):
            out[f"h.{layer}.{hf_key}"] = stacked[layer]
    return out


_HF_LLAMA_BLOCK_KEYS = {
    # HF torch-Linear [out, in] -> our [in, out] kernels: all transposed.
    "input_layernorm.weight": ("ln_attn", "scale"),
    "self_attn.q_proj.weight": ("attn", "wq"),
    "self_attn.k_proj.weight": ("attn", "wk"),
    "self_attn.v_proj.weight": ("attn", "wv"),
    "self_attn.o_proj.weight": ("attn", "wo"),
    "post_attention_layernorm.weight": ("ln_mlp", "scale"),
    "mlp.gate_proj.weight": ("mlp", "gate"),
    "mlp.up_proj.weight": ("mlp", "up"),
    "mlp.down_proj.weight": ("mlp", "down"),
}


# Mixtral expert FFN naming -> our expert leaves. Mixtral computes
# w2(silu(w1(x)) * w3(x)) per expert; our gated expert computes
# (act(x@w_gate) * (x@w_in)) @ w_out (ops/moe._expert_compute), so w1 is
# the activated gate side, w3 the multiplicative up side, w2 the down
# projection.
_MIXTRAL_EXPERT_KEYS = {
    "w_gate": "w1",
    "w_in": "w3",
    "w_out": "w2",
}


def from_hf_llama_state_dict(sd: dict, cfg: ModelConfig) -> dict:
    """Convert an HF LlamaForCausalLM state dict to our llama params.

    All projections are torch Linear [out, in] and transpose to our
    [in, out] kernels. Head ordering and the half-split RoPE convention
    match HF exactly (ops/rope.py), so no permutations are needed. Tied-
    embedding checkpoints (no ``lm_head.weight``, e.g. Llama-3.2 1B) reuse
    ``embed_tokens`` for the head.

    MoE configs (``cfg.n_experts > 0``) import Mixtral-style checkpoints:
    ``block_sparse_moe.gate`` becomes the router and the per-expert
    w1/w3/w2 Linears stack into our [L, X, D, F] / [L, X, F, D] expert
    leaves (``_MIXTRAL_EXPERT_KEYS``). Mixtral's routing — top-k over a
    full softmax, renormalised — is EXACTLY ops/moe._route's top_k>1
    gating (softmax is monotonic, so top-k of probs = top-k of logits,
    and renormalised top-k probs = softmax over the top-k logits), so
    logits parity holds; set cfg.expert_capacity_factor >=
    n_experts/moe_top_k — the exact no-drop bound (capacity scales with
    the k*T assignment count, and each token sends at most ONE assignment
    per expert) — for the dense per-token gather HF implements.
    """
    sd = {k: _to_np(v) for k, v in sd.items()}
    sd = {
        (k[len("model.") :] if k.startswith("model.") else k): v
        for k, v in sd.items()
    }
    dtype = np.dtype(cfg.param_dtype)

    wte = sd["embed_tokens.weight"].astype(dtype)
    if wte.shape != (cfg.vocab_size, cfg.n_embd):
        raise ValueError(
            f"embed_tokens shape {wte.shape} != "
            f"({cfg.vocab_size}, {cfg.n_embd})"
        )
    lm_head = sd.get("lm_head.weight", sd["embed_tokens.weight"])
    params: dict = {
        "wte": wte,
        "ln_f": {"scale": sd["norm.weight"].astype(dtype)},
        "lm_head": lm_head.T.astype(dtype),
        "blocks": {},
    }

    block_keys = dict(_HF_LLAMA_BLOCK_KEYS)
    if cfg.n_experts:
        # Mixtral layers have no dense mlp.* Linears; the MoE leaves are
        # stacked separately below.
        block_keys = {
            k: v for k, v in block_keys.items() if v[0] != "mlp"
        }
    for hf_key, path in block_keys.items():
        per_layer = []
        for layer in range(cfg.n_layer):
            name = f"layers.{layer}.{hf_key}"
            if name not in sd:
                raise KeyError(f"missing {name!r} in state dict")
            arr = sd[name]
            if hf_key.endswith("proj.weight"):
                arr = arr.T  # Linear [out, in] -> kernel [in, out]
            per_layer.append(arr)
        _set_nested(
            params["blocks"], path, np.stack(per_layer).astype(dtype)
        )

    if cfg.n_experts:
        if cfg.moe_top_k == 1:
            # ops/moe._route uses Switch gating at top_k=1 (expert output
            # scaled by the RAW softmax prob); Mixtral renormalises the
            # selected prob to 1.0. The two differ exactly at k=1, so a
            # silent import would break the parity contract. Every
            # released Mixtral uses k=2.
            raise ValueError(
                "Mixtral import needs moe_top_k >= 2: at top_k=1 our "
                "Switch gating (raw prob) differs from Mixtral's "
                "renormalised gating (weight 1.0), so HF parity is "
                "impossible"
            )
        moe = "block_sparse_moe"

        def fetch(name: str) -> np.ndarray:
            # Same missing-key diagnostic as the dense-key loop above, so
            # truncated/mismatched checkpoints (e.g. cfg.n_experts larger
            # than the checkpoint's) fail with the established message.
            if name not in sd:
                raise KeyError(f"missing {name!r} in state dict")
            return sd[name]

        # Router: gate.weight is a torch Linear [X, D] -> our [L, D, X].
        params["blocks"]["mlp"] = {
            "router": np.stack([
                fetch(f"layers.{i}.{moe}.gate.weight").T
                for i in range(cfg.n_layer)
            ]).astype(dtype)
        }
        for ours, hf_w in _MIXTRAL_EXPERT_KEYS.items():
            # Per-expert torch Linears [out, in] -> transposed and stacked
            # over experts then layers: [L, X, in, out].
            stacked = np.stack([
                np.stack([
                    fetch(f"layers.{i}.{moe}.experts.{j}.{hf_w}.weight").T
                    for j in range(cfg.n_experts)
                ])
                for i in range(cfg.n_layer)
            ]).astype(dtype)
            params["blocks"]["mlp"][ours] = stacked
        got_r = params["blocks"]["mlp"]["router"].shape
        expect_r = (cfg.n_layer, cfg.n_embd, cfg.n_experts)
        if got_r != expect_r:
            raise ValueError(
                f"router stacked shape {got_r} != {expect_r} — config "
                "n_experts mismatch with the checkpoint"
            )
        # Expert FFN shapes too: a cfg.n_inner mismatch with the
        # checkpoint's intermediate_size would import cleanly here and
        # only surface later as an opaque matmul shape error in apply().
        expect_e = (cfg.n_layer, cfg.n_experts, cfg.n_embd, cfg.inner_dim)
        for ours in ("w_gate", "w_in"):
            got_e = params["blocks"]["mlp"][ours].shape
            if got_e != expect_e:
                raise ValueError(
                    f"{ours} stacked shape {got_e} != {expect_e} — config "
                    "n_inner/intermediate_size mismatch with the checkpoint"
                )

    got = params["blocks"]["attn"]["wk"].shape
    expect = (cfg.n_layer, cfg.n_embd, cfg.kv_heads * cfg.head_dim)
    if got != expect:
        raise ValueError(
            f"wk stacked shape {got} != {expect} — config kv_heads/head_dim "
            "mismatch with the checkpoint"
        )
    return params


def to_hf_llama_state_dict(params: dict, *, tied: bool | None = None) -> dict:
    """Export our llama-family params to HF naming (torch-Linear [out, in]
    layout, ``model.``-prefixed) — the inverse of
    ``from_hf_llama_state_dict``, for both dense and Mixtral-style MoE
    trees (detected from the params: a ``blocks/mlp/router`` leaf means
    sparse-MoE naming). Produces numpy arrays; wrap in torch tensors to
    load into a transformers model.

    Tied-embedding checkpoints import with ``lm_head`` aliased to the
    embedding table; ``tied=None`` (default) detects that by value
    (head.T == wte) and omits ``lm_head.weight`` the way the tied HF
    checkpoint does, keeping export(import(sd)) == sd exactly for tied
    checkpoints too. The value heuristic is coincidence-prone for an
    UNTIED model whose head still equals its embedding (e.g. export
    straight after a tied-style init) — pass ``tied=False`` (or True) to
    decide explicitly."""
    blocks = params["blocks"]
    wte = np.asarray(params["wte"])
    head = np.asarray(params["lm_head"]).T
    out = {
        "model.embed_tokens.weight": wte,
        "model.norm.weight": np.asarray(params["ln_f"]["scale"]),
    }
    if tied is None:
        tied = np.array_equal(head, wte)
    if not tied:
        out["lm_head.weight"] = head

    def get(path):
        node = blocks
        for p in path:
            node = node[p]
        return np.asarray(node)

    n_layer = get(("ln_attn", "scale")).shape[0]
    moe = "router" in blocks.get("mlp", {})
    block_keys = {
        k: v for k, v in _HF_LLAMA_BLOCK_KEYS.items()
        if not (moe and v[0] == "mlp")
    }
    for hf_key, path in block_keys.items():
        stacked = get(path)
        for layer in range(n_layer):
            arr = stacked[layer]
            if hf_key.endswith("proj.weight"):
                arr = arr.T  # kernel [in, out] -> Linear [out, in]
            out[f"model.layers.{layer}.{hf_key}"] = arr
    if moe:
        router = get(("mlp", "router"))  # [L, D, X]
        n_experts = router.shape[-1]
        # One device-to-host materialisation per expert leaf, not per
        # layer (an 8x7B-scale stack is multi-GB).
        expert_stacks = {
            ours: get(("mlp", ours))  # [L, X, in, out]
            for ours in _MIXTRAL_EXPERT_KEYS
        }
        for layer in range(n_layer):
            base = f"model.layers.{layer}.block_sparse_moe"
            out[f"{base}.gate.weight"] = router[layer].T
            for ours, hf_w in _MIXTRAL_EXPERT_KEYS.items():
                for j in range(n_experts):
                    out[f"{base}.experts.{j}.{hf_w}.weight"] = (
                        expert_stacks[ours][layer, j].T
                    )
    return out


def from_hf_pretrained(model_name: str = "gpt2", cfg: ModelConfig | None = None):
    """Download HF weights and convert (reference from_hf_pretrained,
    my_gpt2.py:292-306, generalised to both families: gpt2-style and
    llama-style checkpoints are detected from the HF config). Needs
    network + transformers; in zero-egress environments convert a local
    state dict via ``from_hf_gpt2_state_dict`` /
    ``from_hf_llama_state_dict`` instead."""
    from transformers import AutoConfig, AutoModelForCausalLM

    from pytorch_distributed_tpu.config import model_config

    hf_cfg = AutoConfig.from_pretrained(model_name)
    is_llama = hf_cfg.model_type in ("llama", "mistral", "mixtral")
    # Mistral-family checkpoints may use sliding-window attention, which
    # this model family does not implement (full causal attention only).
    # Beyond the window the two attention patterns diverge, so the usable
    # context is clamped to the window; logits within it match HF exactly.
    sliding = getattr(hf_cfg, "sliding_window", None)
    if hf_cfg.model_type in ("mistral", "mixtral") and sliding:
        if cfg is not None and cfg.n_ctx > int(sliding):
            # An explicit cfg must stay within the window: beyond it the
            # full-causal logits silently diverge from HF, so refuse
            # rather than import wrong.
            raise ValueError(
                f"cfg.n_ctx={cfg.n_ctx} exceeds {model_name!r}'s sliding "
                f"window ({sliding}); pass cfg with n_ctx <= {sliding} "
                "(full-causal attention diverges from HF beyond it)"
            )
        import warnings

        warnings.warn(
            f"{model_name!r} uses sliding-window attention (window="
            f"{sliding}); importing with full causal attention and "
            f"n_ctx clamped to the window — sequences longer than "
            f"{sliding} tokens are rejected rather than silently wrong.",
            stacklevel=2,
        )
    if cfg is None:
        if is_llama:
            n_ctx = hf_cfg.max_position_embeddings
            if hf_cfg.model_type in ("mistral", "mixtral") and sliding:
                n_ctx = min(n_ctx, int(sliding))
            cfg = model_config("llama3-1b").replace(
                vocab_size=hf_cfg.vocab_size,
                n_ctx=n_ctx,
                n_embd=hf_cfg.hidden_size,
                n_layer=hf_cfg.num_hidden_layers,
                n_head=hf_cfg.num_attention_heads,
                n_kv_head=hf_cfg.num_key_value_heads,
                n_inner=hf_cfg.intermediate_size,
                rope_theta=hf_cfg.rope_theta,
                layer_norm_epsilon=hf_cfg.rms_norm_eps,
            )
            if hf_cfg.model_type == "mixtral":
                # Sparse-MoE shape: capacity at the exact no-drop bound
                # (cf = X/k gives cap = T slots per expert; each token
                # contributes at most one assignment per expert) so our
                # capacity-based dispatch reproduces HF's dense per-token
                # gather exactly with no padding waste.
                cfg = cfg.replace(
                    n_experts=hf_cfg.num_local_experts,
                    moe_top_k=hf_cfg.num_experts_per_tok,
                    expert_capacity_factor=(
                        float(hf_cfg.num_local_experts)
                        / hf_cfg.num_experts_per_tok
                    ),
                )
        else:
            cfg = model_config("gpt2").replace(
                vocab_size=hf_cfg.vocab_size,
                n_ctx=hf_cfg.n_positions,
                n_embd=hf_cfg.n_embd,
                n_layer=hf_cfg.n_layer,
                n_head=hf_cfg.n_head,
            )
    model = AutoModelForCausalLM.from_pretrained(model_name)
    convert = from_hf_llama_state_dict if is_llama else from_hf_gpt2_state_dict
    return convert(model.state_dict(), cfg), cfg
