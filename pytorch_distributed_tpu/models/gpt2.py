"""GPT-2 as pure functions over a params pytree.

Capability twin of the reference's self-contained model
(reference model/my_gpt2.py:10-312): merged-QKV attention, pre-norm residual
blocks, 4x gelu MLP, learned positions, tied LM head, GPT-2 init
(linear N(0,0.02), wpe N(0,0.01), LN w=1/b=0 — reference :216-244), and
per-block selective activation checkpointing (reference :145,175-183).

TPU-first design (NOT a translation of the torch class hierarchy):
- params are a pytree of arrays; block params are **stacked** along a leading
  n_layer axis and the forward pass is a single ``lax.scan`` over layers —
  one compiled block body regardless of depth, and stacked [L, ...] leaves
  shard cleanly under FSDP.
- dense kernels are [in, out] (MXU-natural; HF Conv1D weights import
  transpose-free, unlike reference :254-280 which transposes for nn.Linear).
- remat is ``jax.checkpoint`` around the scanned block with a save-the-dots
  policy (ops/remat.py) — the analogue of compute_intensive_ops.
- dropout uses explicit PRNG keys folded per (step, layer).

Params layout (shapes for config E=n_embd, L=n_layer, V=vocab, C=n_ctx,
F=inner_dim, H=n_head, D=head_dim):
  wte [V, E]; wpe [C, E]
  blocks/ln_1 {scale[L,E], bias[L,E]}     blocks/ln_2 same
  blocks/attn/c_attn {kernel[L,E,3,H,D], bias[L,3,H,D]}
  blocks/attn/c_proj {kernel[L,E,E], bias[L,E]}
  blocks/mlp/c_fc   {kernel[L,E,F], bias[L,F]}
  blocks/mlp/c_proj {kernel[L,F,E], bias[L,E]}
  ln_f {scale[E], bias[E]}
The LM head is weight-tied to wte (reference :206) — no separate leaf.

The merged QKV projection (reference my_gpt2.py:21 stores it as one [E, 3E]
Conv1D) is kept as ONE kernel but shaped [L, E, 3, H, D] with explicit
qkv/head axes: a single MXU matmul still computes all of q/k/v, while
tensor parallelism can shard the HEAD axis — a contiguous split of the
flat 3E dim would cross q/k/v boundaries and cost collective-permutes
between the projection and attention.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.ops.attention import multi_head_attention
from pytorch_distributed_tpu.ops.layer_scan import scan_layers
from pytorch_distributed_tpu.ops.layers import activation, dense, dropout, layer_norm
from pytorch_distributed_tpu.ops.remat import checkpoint_name
from pytorch_distributed_tpu.ops.tp import tp_copy
from pytorch_distributed_tpu.utils.compat import vma_of

Params = dict[str, Any]


def _flash_kernel_active(
    cfg: ModelConfig,
    t: int,
    seq_axis: str | None,
    deterministic: bool = True,
) -> bool:
    """True when attention will run the Pallas kernel, whose (o, l, m)
    outputs the "names" remat policy saves directly. Mirrors every fallback
    in ops/attention.multi_head_attention — including the attention-dropout
    one (training with attn_pdrop>0 runs naive attention)."""
    from pytorch_distributed_tpu.ops.pallas_flash import _pallas_supported

    return (
        cfg.attention_impl == "flash"
        and seq_axis is None
        and (deterministic or cfg.attn_pdrop == 0.0)
        and _pallas_supported(t, t, cfg.head_dim)
    )


def init(key: jax.Array, cfg: ModelConfig) -> Params:
    """GPT-2 initialisation (reference my_gpt2.py:216-244 distributions)."""
    pdt = jnp.dtype(cfg.param_dtype)
    e, l, v, c, f = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.n_ctx, cfg.inner_dim
    h, d = cfg.n_head, cfg.head_dim

    keys = jax.random.split(key, 8)

    def normal(k, shape, std):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * std).astype(pdt)

    def ln(shape):
        return {"scale": jnp.ones(shape, pdt), "bias": jnp.zeros(shape, pdt)}

    return {
        "wte": normal(keys[0], (v, e), 0.02),
        "wpe": normal(keys[1], (c, e), 0.01),
        "blocks": {
            "ln_1": ln((l, e)),
            "attn": {
                "c_attn": {
                    "kernel": normal(keys[2], (l, e, 3, h, d), 0.02),
                    "bias": jnp.zeros((l, 3, h, d), pdt),
                },
                "c_proj": {
                    "kernel": normal(keys[3], (l, e, e), 0.02),
                    "bias": jnp.zeros((l, e), pdt),
                },
            },
            "ln_2": ln((l, e)),
            "mlp": (
                {
                    "c_fc": {
                        "kernel": normal(keys[4], (l, e, f), 0.02),
                        "bias": jnp.zeros((l, f), pdt),
                    },
                    "c_proj": {
                        "kernel": normal(keys[5], (l, f, e), 0.02),
                        "bias": jnp.zeros((l, e), pdt),
                    },
                }
                if not cfg.n_experts
                else {
                    # MoE (ops/moe.py): per-layer router + stacked expert
                    # weights (biasless experts, Switch-style).
                    "router": normal(keys[6], (l, e, cfg.n_experts), 0.02),
                    "w_in": normal(
                        keys[4], (l, cfg.n_experts, e, f), 0.02
                    ),
                    "w_out": normal(
                        keys[5], (l, cfg.n_experts, f, e), 0.02
                    ),
                }
            ),
        },
        "ln_f": ln((e,)),
    }


def _block(
    x: jax.Array,
    bp: Params,
    cfg: ModelConfig,
    layer_key: jax.Array | None,
    deterministic: bool,
    seq_axis: str | None = None,
    tensor_axis: str | None = None,
    expert_axis: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Pre-norm residual block (reference my_gpt2.py:121-134):
    x + attn(ln_1(x)); x + mlp(ln_2(x)). Returns (x, moe_aux_loss) — the
    aux term is zero for the dense MLP.

    ``tensor_axis`` (explicit/shard_map TP): the block computes on its LOCAL
    heads / hidden columns. Megatron f (tp_copy) sits between each norm and
    the column-parallel matmul; the row-parallel projections psum
    (tp_reduce, inside dense) before adding their replicated bias.
    Embd/resid dropout keys are identical across tensor shards, so the
    replicated activations stay bitwise-replicated; the attention-dropout
    key is folded per shard (opt-in via cfg.tensor_dropout="folded") since
    its masks act on head-sharded tensors.
    """
    eps = cfg.layer_norm_epsilon
    b, t = x.shape[:2]

    if layer_key is not None:
        k_attn, k_resid1, k_mlp = jax.random.split(layer_key, 3)
        if tensor_axis is not None:
            # Reached only under cfg.tensor_dropout="folded" (the explicit
            # path rejects attn_pdrop + tensor otherwise): each shard's
            # local heads draw independent attention-dropout masks —
            # statistically equivalent to the single-device draw, not
            # bitwise. k_resid1/k_mlp stay replicated: resid dropout acts
            # on REPLICATED activations, which must stay bitwise-identical
            # across shards for the TP psum algebra to hold.
            k_attn = jax.random.fold_in(
                k_attn, jax.lax.axis_index(tensor_axis)
            )
    else:
        k_attn = k_resid1 = k_mlp = None

    # --- attention sub-block (reference my_gpt2.py:38-77, merged QKV :21) ---
    a = layer_norm(x, bp["ln_1"], eps=eps)
    a = tp_copy(a, tensor_axis)
    # One matmul for q/k/v with explicit qkv/head kernel axes: under tensor
    # parallelism the head axis is sharded and slicing the (replicated)
    # qkv axis needs no resharding.
    qkv = checkpoint_name(dense(a, bp["attn"]["c_attn"]), "qkv")  # [B,T,3,H,D]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    a = multi_head_attention(
        q, k, v,
        impl=cfg.attention_impl,
        causal=True,
        dropout_rate=cfg.attn_pdrop,
        dropout_key=k_attn,
        deterministic=deterministic,
        seq_axis=seq_axis,
        seq_impl=cfg.seq_impl,
    ).reshape(b, t, -1)  # [B, T, E] (E/tp local columns under explicit TP)
    if not _flash_kernel_active(cfg, t, seq_axis, deterministic):
        # On the Pallas path the kernel's o output is already saved by the
        # remat policy (ops/remat._flash_call_policy); tagging here too would
        # store the same tensor twice (~12 MB/layer at bench shapes).
        a = checkpoint_name(a, "attn_out")
    a = checkpoint_name(
        dense(a, bp["attn"]["c_proj"], tp_reduce_axis=tensor_axis),
        "attn_proj",
    )
    a = dropout(a, cfg.resid_pdrop, k_resid1, deterministic=deterministic)
    x = x + a

    # --- MLP sub-block (reference my_gpt2.py:80-99; MoE when n_experts) ---
    m = layer_norm(x, bp["ln_2"], eps=eps)
    if cfg.n_experts:
        from pytorch_distributed_tpu.ops.moe import moe_mlp

        m, aux = moe_mlp(
            m,
            bp["mlp"],
            activation=activation(cfg.activation_function),
            capacity_factor=cfg.expert_capacity_factor,
            expert_axis=expert_axis,
            tensor_axis=tensor_axis,
            top_k=cfg.moe_top_k,
            dispatch_impl=cfg.moe_dispatch,
        )
    else:
        aux = jnp.zeros((), jnp.float32)
        m = tp_copy(m, tensor_axis)
        m = checkpoint_name(dense(m, bp["mlp"]["c_fc"]), "mlp_fc")
        # "mlp_act" is tagged but NOT in the default names policy: saving it
        # trades ~50 MB/layer of HBM for skipping the tanh-gelu recompute in
        # backward — measured a wash at bench shapes (policy A/B hook).
        m = checkpoint_name(
            activation(cfg.activation_function)(m), "mlp_act"
        )
        m = checkpoint_name(
            dense(m, bp["mlp"]["c_proj"], tp_reduce_axis=tensor_axis),
            "mlp_proj",
        )
    m = dropout(m, cfg.resid_pdrop, k_mlp, deterministic=deterministic)
    return x + m, aux


def apply(
    params: Params,
    input_ids: jax.Array,  # [B, T] int
    cfg: ModelConfig,
    *,
    deterministic: bool = True,
    dropout_key: jax.Array | None = None,
    block_transform=None,
    seq_axis: str | None = None,
    tensor_axis: str | None = None,
    expert_axis: str | None = None,
    return_aux: bool = False,
    return_hidden: bool = False,
    prefetch_buffers: int = 0,
) -> jax.Array:
    """Forward pass: [B, T] token ids -> [B, T, V] float32 logits.
    With ``return_aux=True`` returns (logits, moe_aux_loss) — the summed
    Switch load-balancing term over layers (zero for dense configs).
    With ``return_hidden=True`` the head matmul is skipped and the
    final-norm hidden states [B, T, E] come back in place of logits — the
    input the fused head+cross-entropy loss consumes (config
    ``fused_head_ce``).

    Mirrors reference my_gpt2.py:163-188 (trunk) + :211-213 (tied head):
    wte + wpe -> embd dropout -> n_layer pre-norm blocks -> ln_f -> tied head.

    ``block_transform``, if given, maps each layer's sliced param subtree
    before use inside the scan — the hook explicit FSDP uses for just-in-time
    per-layer all_gather (parallel/explicit.py); remat then re-gathers in
    backward, matching FSDP's free-after-use behavior.

    ``prefetch_buffers``: latency-hiding window for the block_transform
    gathers — layer l+1..l+N's transforms are issued before layer l's
    compute (ops/layer_scan.py). Bit-equivalent to the default
    just-in-time schedule; soft-sized to a divisor of n_layer.

    ``seq_axis``: set when called inside shard_map with the sequence dim
    sharded over that mesh axis (context parallelism): positions are offset
    by this shard's global start and attention runs the ring kernel.

    ``tensor_axis``: set when called inside shard_map with block params
    sharded Megatron-style over that mesh axis (explicit tensor
    parallelism): blocks compute on local heads/columns with tp_copy /
    tp_reduce at the region boundaries; embeddings, norms, and the tied
    head are replicated.
    """
    if not deterministic and dropout_key is None:
        raise ValueError("training-mode apply() requires dropout_key")
    b, t = input_ids.shape
    # Under sequence sharding the GLOBAL length (shards × local t) must fit
    # the position table — dynamic_slice would silently clamp past-the-end
    # shards onto the last wpe rows otherwise.
    global_t = t * (jax.lax.psum(1, seq_axis) if seq_axis is not None else 1)
    if global_t > cfg.n_ctx:
        raise ValueError(
            f"sequence length {global_t} exceeds n_ctx {cfg.n_ctx}"
        )
    dtype = jnp.dtype(cfg.dtype)

    if seq_axis is not None:
        # Local shard covers global positions [idx*t, (idx+1)*t).
        pos_start = jax.lax.axis_index(seq_axis) * t
        wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos_start, t, axis=0)
    else:
        wpe = params["wpe"][:t]
    x = params["wte"][input_ids] + wpe
    x = x.astype(dtype)
    if not deterministic:
        dropout_key, k_embd = jax.random.split(dropout_key)
        x = dropout(x, cfg.embd_pdrop, k_embd, deterministic=False)

    # Scan over stacked block params; remat each block (or prefetch
    # window) body — ops/layer_scan.py. The per-layer dropout key is
    # folded from (dropout_key, layer_index) inside the scan.
    def block_body(carry, bp, layer_idx):
        h, aux_sum = carry
        layer_key = (
            None
            if deterministic
            else jax.random.fold_in(dropout_key, layer_idx)
        )
        h, aux = _block(
            h, bp, cfg, layer_key, deterministic, seq_axis, tensor_axis,
            expert_axis,
        )
        return (h, aux_sum + aux)

    layer_ids = jnp.arange(cfg.n_layer)
    # The aux carry must vary on every axis the activations vary on (any
    # sharded batch/param axis under shard_map), not just the expert axis —
    # scan requires carry input/output vma to match.
    from pytorch_distributed_tpu.ops.tp import pvary_missing

    aux0 = pvary_missing(
        jnp.zeros((), jnp.float32),
        tuple(vma_of(x)),
    )
    x, aux_total = scan_layers(
        block_body, (x, aux0), params["blocks"], layer_ids,
        remat_mode=cfg.remat,
        block_transform=block_transform,
        prefetch_buffers=prefetch_buffers,
        unroll=cfg.scan_unroll,
    )
    if return_hidden:
        out = final_norm(params, x, cfg)
    else:
        out = head(params, x, cfg)
    if return_aux:
        return out, aux_total
    return out


# -- phase functions (pipeline parallelism, parallel/pipeline.py) ----------
# The same forward pass split at the stage boundaries GPipe partitions at:
# embed | n_layer blocks | head; apply() ends by calling head() so the two
# paths cannot drift. Deterministic mode only (the pipeline path rejects
# dropout configs at build time).


def embed(
    params: Params,
    input_ids: jax.Array,
    cfg: ModelConfig,
    *,
    seq_axis: str | None = None,
) -> jax.Array:
    """``seq_axis``: sequence-sharded (context-parallel) call — the local
    [B, T/N] token shard takes position rows [idx*T/N, (idx+1)*T/N) of the
    learned table, exactly like ``apply``'s seq path."""
    b, t = input_ids.shape
    global_t = t * (jax.lax.psum(1, seq_axis) if seq_axis is not None else 1)
    if global_t > cfg.n_ctx:
        raise ValueError(
            f"sequence length {global_t} exceeds n_ctx {cfg.n_ctx}"
        )
    if seq_axis is not None:
        pos_start = jax.lax.axis_index(seq_axis) * t
        wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos_start, t, axis=0)
    else:
        wpe = params["wpe"][:t]
    x = params["wte"][input_ids] + wpe
    return x.astype(jnp.dtype(cfg.dtype))


def run_blocks(
    blocks: Params, x: jax.Array, cfg: ModelConfig, *, block_transform=None,
    return_aux: bool = False, tensor_axis: str | None = None,
    expert_axis: str | None = None, seq_axis: str | None = None,
    dropout_key: jax.Array | None = None,
    deterministic: bool = True, layer_offset=0,
    prefetch_buffers: int = 0,
):
    """Scan a stack of [L_local, ...] block params over x (L_local may be a
    pipeline stage's slice of the full depth). With ``return_aux=True``
    returns (x, aux) — the summed Switch load-balancing term over the LOCAL
    layers (zero for dense configs); the pipeline path psums it over the
    stage axis.

    ``block_transform`` (e.g. a per-layer fsdp all_gather) runs on each
    sliced layer INSIDE the rematted body, so backward re-gathers instead
    of saving gathered params (same contract as ``apply``'s).

    ``tensor_axis``: blocks compute Megatron-style on their local
    heads/columns with tp_copy/tp_reduce at the region boundaries
    (in-stage TP for the pipeline path). ``expert_axis``: MoE expert
    weights shard over it and tokens route through the all_to_all
    exchange (in-stage EP).

    ``seq_axis``: sequence-sharded (context-parallel) call — x holds the
    local token shard and attention runs the ring/ulysses kernel over the
    axis (in-stage seq for the pipeline path).

    ``dropout_key``/``deterministic``/``layer_offset``: training-mode
    dropout for the pipeline path. Per-layer keys fold exactly like
    ``apply``'s — fold_in(dropout_key, GLOBAL layer index) — so a pipe
    stage passing its ``layer_offset`` (stage * layers_per_stage, may be
    traced) draws the same masks the single-device forward would."""
    from pytorch_distributed_tpu.ops.tp import pvary_missing

    if not deterministic and dropout_key is None:
        raise ValueError("training-mode run_blocks requires dropout_key")

    def block_body(carry, bp, layer_idx):
        h, aux_sum = carry
        layer_key = (
            None
            if deterministic
            else jax.random.fold_in(dropout_key, layer_offset + layer_idx)
        )
        h, aux = _block(
            h, bp, cfg, layer_key, deterministic, seq_axis, tensor_axis,
            expert_axis,
        )
        return (h, aux_sum + aux)

    aux0 = pvary_missing(
        jnp.zeros((), jnp.float32),
        tuple(vma_of(x)),
    )
    n_local = jax.tree.leaves(blocks)[0].shape[0]
    x, aux_total = scan_layers(
        block_body, (x, aux0), blocks, jnp.arange(n_local),
        remat_mode=cfg.remat,
        block_transform=block_transform,
        prefetch_buffers=prefetch_buffers,
    )
    if return_aux:
        return x, aux_total
    return x


def final_norm(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """ln_f alone — the hidden states the fused head+CE loss consumes
    (the pipeline path's last stage calls this instead of ``head`` when
    cfg.fused_head_ce)."""
    return layer_norm(x, params["ln_f"], eps=cfg.layer_norm_epsilon)


def head(params: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    x = final_norm(params, x, cfg)
    # Tied LM head (reference my_gpt2.py:200-206): logits = x @ wte^T. The MXU
    # accumulates in f32; cfg.logits_dtype controls what lands in HBM.
    logits = jnp.einsum(
        "bte,ve->btv", x, params["wte"].astype(x.dtype),
        preferred_element_type=jnp.float32,
    )
    return logits.astype(jnp.dtype(cfg.logits_dtype))
