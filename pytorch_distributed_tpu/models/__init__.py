"""Model families: pure ``init(key, cfg) -> params`` / ``apply(params, ids, cfg)``."""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax

from pytorch_distributed_tpu.config import ModelConfig


class ModelApi(NamedTuple):
    init: Callable[[jax.Array, ModelConfig], dict]
    apply: Callable[..., jax.Array]
    # Phase functions — the same forward split at pipeline-stage boundaries
    # (embed | blocks | head), used by parallel/pipeline.py.
    embed: Callable[..., jax.Array]
    run_blocks: Callable[..., jax.Array]
    head: Callable[..., jax.Array]
    # (params) -> (head weight array, ops.losses layout tag): the LM-head
    # matrix the fused head+CE loss multiplies against — tied wte [V, E]
    # ("ve") for gpt2, untied lm_head [E, V] ("ev") for llama.
    head_weight: Callable[[dict], tuple[jax.Array, str]]
    # ln_f alone — head() minus the vocab matmul; what the fused head+CE
    # loss consumes on the pipeline path's last stage.
    final_norm: Callable[..., jax.Array]


def get_model(cfg: ModelConfig) -> ModelApi:
    if cfg.family == "gpt2":
        from pytorch_distributed_tpu.models import gpt2

        return ModelApi(
            gpt2.init, gpt2.apply, gpt2.embed, gpt2.run_blocks, gpt2.head,
            lambda params: (params["wte"], "ve"),
            gpt2.final_norm,
        )
    if cfg.family == "llama":
        from pytorch_distributed_tpu.models import llama

        return ModelApi(
            llama.init, llama.apply, llama.embed, llama.run_blocks,
            llama.head,
            lambda params: (params["lm_head"], "ev"),
            llama.final_norm,
        )
    raise KeyError(f"unknown model family {cfg.family!r}")
