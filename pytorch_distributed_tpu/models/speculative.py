"""Prompt-lookup speculative decoding: the host-side drafter + the
single-sequence monolithic reference loop.

Since the batched-speculation PR the SERVING implementation lives in
``serving/engine.py``: ``BatchedDecodeEngine`` /
``PagedBatchedDecodeEngine`` built with ``speculative_k=K`` draft k
tokens per row host-side (``prompt_lookup_draft`` below), verify every
row's drafts in ONE batched ``decode_spec_step`` forward with per-row
traced accept lengths (models/decode.speculative_accept), and roll back
rejected drafts by simply not advancing the row past its accepted depth
— on the paged engine that truncation confines speculative garbage to
the row's private tail page. ``scripts/generate.py --speculative``
routes through that engine path for dense configs. This module keeps:

- ``prompt_lookup_draft`` — the numpy n-gram drafter the engines call
  per row per tick (and the one place its semantics live, so the
  host and traced lookups cannot drift);
- ``generate_speculative`` — the original one-jit greedy loop, kept as
  the bit-pinned REFERENCE the engine path is equivalence-tested
  against (tests/test_speculative.py + tests/test_serving_spec.py) and
  as the MoE fallback (the batched engines reject MoE configs: expert
  capacity couples rows).

Speculative decoding amortises the per-step HBM cost of autoregressive
generation: batched-1 decode is bandwidth-bound (every step streams the
full parameter set for ONE matmul row — benchmarks/PERF_NOTES.md "Decode
throughput"), so verifying K draft tokens in one forward costs barely
more than generating one token, and every accepted draft is a step's
worth of weight traffic saved. The classic scheme drafts with a smaller
model; prompt-lookup drafting (the HF ``prompt_lookup_num_tokens``
technique) instead proposes the continuation of the most recent earlier
occurrence of the current n-gram — free to produce, and highly effective
on self-repetitive text (code, extraction, summarisation with quotes).

Exactness: the verifier accepts draft[j] only while every earlier draft
matched the model's own greedy choice, then appends the model's next
token itself — the output is a greedy decode of the model; draft quality
only changes speed. In float32 it is BITWISE the plain
``decode.generate`` output (``tests/test_speculative.py`` pins equality
on adversarial and repetitive inputs for both families). In reduced
precision (bf16) the 1-token and K+1-token forwards are differently
shaped programs whose logits can round near-ties differently, so the two
decodes may diverge AT a near-tie (measured on TPU; the same caveat
applies to any speculative scheme, incl. HF's) — each output is still
greedy for its own program's logits.

TPU-first mechanics (everything static-shaped inside one jit):
- the n-gram search is a vectorised compare over the fixed-size output
  buffer (a [total, ngram] gather + all-reduce, no Python scanning);
- each loop iteration runs ONE ``decode.forward`` of K+1 tokens (the
  current last token + K drafts) against the shared KV cache. The cache
  rows K+1 forward writes for rejected drafts are harmless: attention
  masks key positions > pos, and the next iteration's write at the same
  offsets overwrites them (models/decode.py cache discipline);
- acceptance folds into the ``lax.while_loop`` carry as a traced token
  count; the output buffer is updated with a masked scatter
  (``mode="drop"``), so overshoot past ``max_new_tokens`` is clipped.

The loop is greedy-only: temperature sampling needs rejection-sampling
corrections to stay distribution-exact, which is out of scope here and
rejected loudly. Single sequence (B=1): acceptance length varies per
row, which would need per-row cache offsets; batch the PROMPTS instead.

Why this REFERENCE loop keeps its KV cache jit-internal (the serving
engines donate theirs): the verify loop is a ``lax.while_loop`` whose
per-iteration forward length is K+1 and whose trip count depends on
acceptance — the cache never crosses a program boundary, so there is
nothing to donate ACROSS; splitting the loop into per-iteration
dispatches is exactly what the engine path does, paying one host
round-trip per verify step to buy continuous batching, the donated
paged pool, and the fault model. Single-sequence latency-only callers
lose nothing here; everything serving-shaped goes through the engine.
The decision is pinned where it can't rot: tests/test_speculative.py
asserts bit-equivalence against BOTH the monolithic greedy reference
and the serving engine's greedy output, and tests/test_serving_spec.py
pins the batched engine path against this loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.models import decode


def prompt_lookup_draft(
    tokens: np.ndarray, k: int, ngram: int = 2
) -> np.ndarray:
    """Host-side prompt-lookup drafter (the HF
    ``prompt_lookup_num_tokens`` technique): find the most recent
    EARLIER occurrence of the trailing ``ngram`` of ``tokens`` and
    return up to ``k`` tokens that followed it ([<=k] int32; empty when
    no match or history is shorter than the n-gram). Shared by the
    batched serving engines (one call per greedy row per tick — numpy,
    zero model cost) and semantically identical to the traced
    ``_lookup_draft`` the monolithic reference uses: windows fully
    inside the known prefix, the trailing n-gram itself excluded, most
    recent match wins. Drafts are proposals only — the verify forward
    is the ground truth — so this function can never affect output
    tokens, only speed."""
    tokens = np.asarray(tokens, np.int32)
    n = tokens.shape[0]
    if k < 1 or n <= ngram:
        return np.zeros((0,), np.int32)
    tail = tokens[-ngram:]
    windows = np.lib.stride_tricks.sliding_window_view(tokens, ngram)
    # Candidate windows end strictly before the tail starts the match
    # position: starts 0..n-ngram-1 (the final window IS the tail).
    hits = np.nonzero(np.all(windows[:-1] == tail[None, :], axis=1))[0]
    if hits.size == 0:
        return np.zeros((0,), np.int32)
    best = int(hits[-1])  # most recent match = closest context
    return tokens[best + ngram : best + ngram + k].copy()


def _lookup_draft(out_buf, pos, *, ngram: int, draft_len: int, total: int):
    """Find the most recent earlier occurrence of the trailing ``ngram``
    of ``out_buf[0, :pos]`` and return the ``draft_len`` tokens that
    followed it ([draft_len] int32; zeros when no match).

    All shapes static: windows are gathered for every position of the
    buffer and invalid ones (beyond the generated prefix, or the trailing
    n-gram itself) are masked out.
    """
    seq = out_buf[0]  # [total]
    # The n-gram to match: seq[pos-ngram : pos] via clipped gather.
    tail_idx = pos - ngram + jnp.arange(ngram)
    tail = jnp.take(seq, tail_idx, mode="clip")  # [ngram]

    # Window i covers seq[i : i+ngram]; candidate drafts follow at
    # seq[i+ngram : i+ngram+draft_len].
    starts = jnp.arange(total)  # [total]
    win_idx = starts[:, None] + jnp.arange(ngram)[None, :]
    windows = jnp.take(seq, win_idx, mode="clip")  # [total, ngram]
    matches = jnp.all(windows == tail[None, :], axis=1)

    # Valid window: fully inside the known prefix, not the tail itself,
    # and with at least one known token after it to draft from.
    valid = (starts + ngram < pos) & (starts >= 0)
    hit = matches & valid
    # Most recent match wins (closest context). -1 = no match.
    best = jnp.max(jnp.where(hit, starts, -1))

    draft_idx = best + ngram + jnp.arange(draft_len)
    draft = jnp.take(seq, draft_idx, mode="clip")
    # Drafted positions at/after pos are unknown future — zero them so a
    # no-match or short-history draft is deterministic garbage (the
    # verifier rejects it; correctness never depends on the draft).
    known = (best >= 0) & (draft_idx < pos)
    return jnp.where(known, draft, 0).astype(jnp.int32)


# repolint: allow(jit-donation-decision) — params are the serving
# weights, reused by every speculative-decode call; the KV cache is
# deliberately jit-internal (the verify while_loop never crosses a
# program boundary — see module docstring "Why the KV cache stays
# jit-internal"), so there is no donated-cache variant to prefer.
@partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "draft_len", "ngram",
                     "max_len"),
)
def _speculative_impl(
    params, prompt, cfg, max_new_tokens, draft_len, ngram, max_len
):
    b, tp = prompt.shape
    total = tp + max_new_tokens

    cache = decode.init_cache(cfg, b, max_len)
    out = jnp.zeros((b, total), jnp.int32)
    out = jax.lax.dynamic_update_slice(out, prompt.astype(jnp.int32), (0, 0))

    # Prefill + first token (same as the plain greedy loop).
    logits, cache = decode.forward(params, prompt, cfg, cache, 0)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    out = out.at[:, tp].set(first)
    pos = jnp.asarray(tp + 1, jnp.int32)  # tokens known so far

    def cond(carry):
        _, _, pos = carry
        return pos < total

    def body(carry):
        out, cache, pos = carry
        draft = _lookup_draft(
            out, pos, ngram=ngram, draft_len=draft_len, total=total
        )  # [K]
        last = jax.lax.dynamic_slice(out, (0, pos - 1), (b, 1))  # [1, 1]
        tokens_in = jnp.concatenate([last, draft[None, :]], axis=1)  # [1,K+1]
        logits, cache = decode.forward(
            params, tokens_in, cfg, cache, pos - 1
        )
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [1, K+1]
        # greedy[0, j] is the model's next token after tokens_in[0, j];
        # draft[j] survives iff all earlier drafts matched the model.
        match = draft == greedy[0, :draft_len]
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
        # Accepted drafts plus the model's own next token ("bonus"): the
        # new tokens are greedy[0, :n_acc+1] — for j < n_acc these equal
        # draft[j], and greedy[0, n_acc] is the correction/continuation.
        positions = pos + jnp.arange(draft_len + 1)
        keep = jnp.arange(draft_len + 1) <= n_acc
        write_pos = jnp.where(
            keep & (positions < total), positions, total  # total = dropped
        )
        out = out.at[0, write_pos].set(greedy[0], mode="drop")
        return out, cache, pos + n_acc + 1

    out, _, _ = jax.lax.while_loop(cond, body, (out, cache, pos))
    return out


def generate_speculative(
    params,
    prompt: jax.Array,  # [1, Tp] int — single sequence
    cfg: ModelConfig,
    max_new_tokens: int,
    *,
    draft_len: int = 8,
    ngram: int = 2,
) -> jax.Array:
    """Greedy generation with prompt-lookup speculative decoding.

    Returns [1, Tp + max_new_tokens] — bitwise identical to
    ``decode.generate(..., temperature=0)`` in float32; in bf16 the two
    programs may round near-tied logits differently (module docstring).
    Drafts only change speed.
    ``draft_len`` (K) is the speculation depth: each loop iteration
    verifies K drafted tokens in one K+1-token forward and commits
    between 1 and K+1 tokens. ``ngram`` is the lookup width (2 is the
    HF default; longer n-grams are more precise, match less often).
    """
    if prompt.ndim != 2 or prompt.shape[0] != 1:
        raise ValueError(
            "speculative decoding is single-sequence ([1, Tp] prompts): "
            "per-row acceptance lengths would need per-row cache offsets "
            f"(got shape {tuple(prompt.shape)})"
        )
    if draft_len < 1:
        raise ValueError(f"draft_len must be >= 1, got {draft_len}")
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if max_new_tokens == 0:
        return prompt.astype(jnp.int32)
    tp = prompt.shape[1]
    total = tp + max_new_tokens
    # The verify forward may write up to draft_len rows past the last
    # needed position; the cache (and position tables) must cover them.
    max_len = total + draft_len
    if max_len > cfg.n_ctx:
        raise ValueError(
            f"prompt + max_new_tokens + draft_len = {max_len} exceeds "
            f"n_ctx {cfg.n_ctx}; shorten the generation or draft_len"
        )
    return _speculative_impl(
        params, prompt, cfg, max_new_tokens, draft_len, ngram, max_len
    )
