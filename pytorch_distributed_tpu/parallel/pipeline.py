"""Pipeline parallelism (GPipe-style) over a "pipe" mesh axis.

Beyond-reference capability (the reference has no PP at all, SURVEY.md §2.2):
the transformer's depth is partitioned across pipeline stages — stage s owns
layers [s·L/S, (s+1)·L/S) as its slice of the stacked [L, ...] block params —
and the gradient-accumulation microbatches stream through the stages:

  tick t: stage s processes microbatch (t - s); activations hop to the next
  stage over ``lax.ppermute`` (ICI neighbour exchange). M microbatches over
  S stages take M + S - 1 ticks; the (S-1)-tick bubble is GPipe's.

The whole schedule is ONE ``lax.scan`` inside ``shard_map``, so reverse-mode
AD mechanically yields the backward pipeline: the transpose of the scan runs
ticks in reverse and the transpose of each ppermute is the reverse hop —
no hand-written backward schedule. Stage 0 embeds, the last stage runs the
LM head + loss (gated with ``lax.cond`` so other stages skip the
vocab-sized matmul); bubble ticks compute on garbage whose loss contribution
— and therefore gradient — is exactly zero.

Composes with the data axis (DDP: batch rows shard over "data", grads
pmean over it) and with the FULL in-stage ZeRO ladder over "fsdp":
strategy="full_shard" (ZeRO-3: stage params/opt-state shard, each scanned
layer all_gathers just in time inside the rematted body and the gather's
AD transpose reduce-scatters the grads), "shard_grad_op" (ZeRO-2: params
replicated in compute, grads reduce-scattered, sharded Adam +
re-materialise), "shard_opt" (ZeRO-1: all-reduced grads, sharded Adam),
"no_shard" (fsdp as a plain extra data axis) — the same machinery as
parallel/explicit.py, whose helpers are reused. Global-norm grad clipping
is applied against the pipe/fsdp-aware psum'd norm. MoE models run either
with experts replicated within each stage or with in-stage EXPERT
parallelism over "expert" (each stage's expert weights shard, its local
tokens route through the all_to_all exchange, and "expert" doubles as a
batch axis — the placement real MoE training uses); every stage adds its
local layers' Switch aux term to its loss (bubble ticks gated out), and
the loss psum over "pipe" assembles CE + aux exactly as the
single-device step does. In-stage Megatron TP over "tensor" (classic 3D
parallelism): block params shard head-/column-aligned per
parallel/sharding.py's rule table, blocks compute on local heads with
the tp_copy/tp_reduce conjugates, and the norm/clip machinery psums
tensor-sharded leaves' contributions over "tensor". Dropout trains too:
per-microbatch keys fold exactly like the single-device step's (fold per
accum index, split off the embd key, fold per GLOBAL layer id), so
pipe-only meshes reproduce its masks BITWISE; batch-sharded meshes fold
each sharded batch axis's index into the key so every global row draws
an INDEPENDENT mask (iid, like single-device training — the explicit
path's convention; not bitwise vs single device). In-stage SEQUENCE
parallelism over "seq" (PP x SP — the standard long-context large-model
layout): the token dim of every microbatch shards over "seq", stage 0
embeds its position slice (wpe offset / RoPE offset), attention runs
the ring or Ulysses kernel whose collectives ride the "seq" axis —
orthogonal to the pipeline's own "pipe" ppermute, and uniform within
each seq ring even under 1F1B's per-stage cond gating (seq peers always
share a stage, so they agree on every schedule predicate) — and the
last stage's local-token loss is pmean'd over "seq" at the boundary.

Typed under check_vma: block params vary over "pipe" (sharded), replicated
leaves (embeddings, final norm, head) are pvaried for local differentiation
and their per-stage partial grads are psum'd over "pipe" at the boundary —
stage contributions are disjoint (embed grad lives on stage 0, head grad on
the last stage), so the psum reconstructs the exact full gradient.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.utils.compat import shard_map, vma_of

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig, TrainConfig
from pytorch_distributed_tpu.models import ModelApi
from pytorch_distributed_tpu.ops.losses import (
    cross_entropy_loss,
    linear_cross_entropy,
)
from pytorch_distributed_tpu.ops.tp import pvary_missing
from pytorch_distributed_tpu.parallel.mesh import fold_batch_shard_key
from pytorch_distributed_tpu.parallel.zero import (
    clip_by_global_norm_typed,
    gather_params,
    scatter_grads,
    spec_has as _has_axis,
    zero_sharded_update,
)
from pytorch_distributed_tpu.train.state import TrainState


def pipeline_state_specs(state: TrainState, mesh_cfg: MeshConfig):
    """Block leaves shard their stacked layer dim over "pipe"; everything
    else replicates over pipe.

    In-stage sharding reuses parallel/sharding.py's rule table
    (``_leaf_spec``): tensor > 1 claims each block leaf's Megatron dim
    (head-aligned QKV, row/column-parallel projections, expert FFNs);
    the in-stage ZeRO ladder then shards the largest remaining divisible
    weight dim over "fsdp" — strategy="full_shard" (ZeRO-3) for params
    AND optimizer moments (block leaves never their pipe-owned layer dim,
    embedding tables never their vocab/position dim); "shard_grad_op"
    (ZeRO-2) and "shard_opt" (ZeRO-1) keep params replicated over fsdp
    but shard the optimizer moments in the layout params WOULD have under
    full_shard; "no_shard" treats fsdp as a plain extra data axis."""
    from pytorch_distributed_tpu.parallel.sharding import _leaf_spec

    fsdp_params = mesh_cfg.strategy == "full_shard"
    fsdp_opt = mesh_cfg.strategy in (
        "full_shard", "shard_grad_op", "shard_opt"
    )

    def make_spec_for(shard_fsdp):
        def spec_for(path, leaf):
            keys = [getattr(p, "key", None) for p in path]
            ndim = getattr(leaf, "ndim", 0)
            shape = tuple(getattr(leaf, "shape", ()))
            if ndim == 0:
                return P()
            stacked = "blocks" in keys
            embedding = bool(keys) and keys[-1] in ("wte", "wpe")
            base = _leaf_spec(
                shape,
                mesh_cfg,
                path=path,
                shard_fsdp=shard_fsdp,
                min_dim=1 if (stacked or embedding) else 0,
            )
            spec = list(base) + [None] * (ndim - len(base))
            if stacked:
                assert spec[0] is None, (keys, spec)
                spec[0] = "pipe"
            if all(ax is None for ax in spec):
                return P()
            return P(*spec)

        return spec_for

    p_specs = jax.tree_util.tree_map_with_path(
        make_spec_for(fsdp_params), state.params
    )
    o_specs = jax.tree_util.tree_map_with_path(
        make_spec_for(fsdp_opt), state.opt_state
    )
    return TrainState(params=p_specs, opt_state=o_specs, step=P())


def shard_pipeline_state(state: TrainState, mesh: Mesh, mesh_cfg: MeshConfig):
    specs = pipeline_state_specs(state, mesh_cfg)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(state, shardings), shardings


def make_pipeline_train_step(
    model: ModelApi,
    model_cfg: ModelConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    mesh_cfg: MeshConfig,
    state: TrainState,
    train_cfg: TrainConfig | None = None,
    *,
    schedule: str = "gpipe",
    grad_clip_norm: float | None = None,
) -> Callable:
    """Build the jitted pipelined (state, batch, key) -> (state, metrics)
    step. ``batch`` is [M, B_global, T]; M (the grad-accumulation factor)
    doubles as the pipeline microbatch count. State must be placed by
    ``shard_pipeline_state``.

    ``schedule``: "gpipe" (forward scan, backward obtained by AD
    transposition — lowest compute, activation stash grows with M) or
    "1f1b" (hand-scheduled PipeDream-flush: backward starts as soon as a
    microbatch clears the last stage, bounding the activation stash at S
    slots at the cost of one full-stage recompute per backward tick).
    Both produce identical numbers (equivalence-tested).

    ``grad_clip_norm``: global-norm gradient clipping, computed from the
    pipe/fsdp-aware global norm (per-leaf squared sums psum'd over exactly
    the axes each leaf is sharded over), so every stage applies the SAME
    clip scale. The ``tx`` passed in must be clip-free
    (``make_optimizer(cfg, with_clip=False)``) — optax's clip inside
    shard_map would compute a stage-local norm, silently applying a
    different scale per stage (same contract as
    parallel/explicit.py:make_explicit_train_step)."""
    if mesh_cfg.pipe <= 1:
        raise ValueError("pipeline path needs mesh_cfg.pipe > 1")
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(
            f"unknown pipeline schedule {schedule!r} (gpipe, 1f1b)"
        )
    if (
        train_cfg is not None
        and train_cfg.grad_clip_norm
        and grad_clip_norm is None
    ):
        # The caller's tx was presumably built WITH optax's clip element,
        # which inside shard_map would clip against a stage-LOCAL norm.
        raise ValueError(
            "grad_clip_norm on the pipeline path must be applied by this "
            "step against the pipe-aware global norm: build the optimizer "
            "with make_optimizer(cfg, with_clip=False) and pass "
            "grad_clip_norm= explicitly"
        )
    strategy = mesh_cfg.strategy
    # The llama family is dropout-free BY DESIGN (its apply()/run_blocks
    # ignore dropout keys entirely); the pipeline's orchestration-level
    # embedding dropout must match that, or a llama config with nonzero
    # pdrop fields would train a noised model the single-device step never
    # sees. gpt2 is the only family with dropout semantics.
    train_mode = model_cfg.family == "gpt2" and (
        model_cfg.embd_pdrop > 0
        or model_cfg.attn_pdrop > 0
        or model_cfg.resid_pdrop > 0
    )
    if (
        train_mode
        and mesh_cfg.tensor > 1
        and model_cfg.attn_pdrop > 0
        and model_cfg.tensor_dropout != "folded"
    ):
        # Same contract as parallel/explicit.py: attention-dropout masks
        # act on head-sharded tensors, so in-stage TP needs the per-shard
        # folded-key opt-in. Gated on train_mode so llama configs (which
        # ignore dropout fields entirely) are not spuriously rejected.
        raise NotImplementedError(
            "attention dropout with in-stage tensor parallelism needs "
            "cfg.tensor_dropout='folded' (or attn_pdrop=0.0)"
        )
    if (
        train_mode
        and mesh_cfg.seq > 1
        and model_cfg.attn_pdrop > 0
        and model_cfg.seq_impl != "ulysses"
    ):
        # Same build-time contract as the explicit path's seq check:
        # ulysses supports attention dropout (per-seq-shard keys via
        # fold_batch_shard_key, ops/ulysses.py); ring does not (weights
        # only exist per KV block inside the online-softmax merge).
        raise NotImplementedError(
            "attention dropout is not supported with in-stage ring-"
            f"attention sequence parallelism (attn_pdrop="
            f"{model_cfg.attn_pdrop}); set attn_pdrop=0.0 or use "
            "seq_impl='ulysses'"
        )
    if mesh_cfg.expert > 1:
        if not model_cfg.n_experts:
            raise ValueError(
                "expert axis > 1 needs an MoE model (n_experts > 0)"
            )
        if model_cfg.n_experts % mesh_cfg.expert:
            raise ValueError(
                f"n_experts={model_cfg.n_experts} not divisible by "
                f"expert={mesh_cfg.expert}"
            )
    n_stages = mesh_cfg.pipe
    if model_cfg.n_layer % n_stages != 0:
        raise ValueError(
            f"n_layer={model_cfg.n_layer} not divisible by "
            f"pipe={n_stages} stages"
        )
    data_axis = "data" if mesh_cfg.data > 1 else None
    tensor_axis = "tensor" if mesh_cfg.tensor > 1 else None
    expert_axis = "expert" if mesh_cfg.expert > 1 else None
    seq_axis = "seq" if mesh_cfg.seq > 1 else None
    fsdp_size = mesh_cfg.fsdp
    # No wrap-around pair: stage 0 always takes the embed branch, so shipping
    # the last stage's activation back to it would be a wasted hop; ppermute
    # delivers zeros to stages with no source, which stage 0 ignores.
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    specs = pipeline_state_specs(state, mesh_cfg)
    # ZeRO-2/1 slice replicated params/grads into the layout they WOULD
    # have under full_shard (explicit-path contract, explicit.py:188-192).
    if strategy in ("shard_grad_op", "shard_opt") and fsdp_size > 1:
        shard_param_specs = pipeline_state_specs(
            state, dataclasses.replace(mesh_cfg, strategy="full_shard")
        ).params
    else:
        shard_param_specs = None
    # fsdp is data parallelism with sharded state: batch rows split over it;
    # in-stage seq (context parallelism) shards the TOKEN dim.
    batch_axes = tuple(
        ax
        for ax in ("data", "fsdp", "expert")
        if getattr(mesh_cfg, ax) > 1
    ) or None
    batch_spec = P(None, batch_axes, seq_axis)

    vary_axes = ("pipe",) + tuple(
        ax
        for ax in ("data", "fsdp", "expert", "seq")
        if getattr(mesh_cfg, ax) > 1
    )

    def _vary(x):
        return pvary_missing(x, vary_axes)

    if fsdp_size > 1 and strategy == "full_shard":
        # In-stage ZeRO-3: non-block leaves gather up front; each scanned
        # layer gathers its own block slice just in time inside the
        # (rematted) scan body — backward re-gathers and the gather's AD
        # transpose IS the gradient reduce-scatter (same machinery as
        # parallel/explicit.py, whose helpers are reused).

        block_specs = jax.tree.map(
            lambda s: P(*s[1:]),
            specs.params["blocks"],
            is_leaf=lambda x: isinstance(x, P),
        )

        def gather_block(bp):
            return gather_params(bp, block_specs)

        def gather_nonblock(params):
            return {
                k: (v if k == "blocks" else gather_params(v, specs.params[k]))
                for k, v in params.items()
            }

    else:
        gather_block = None

        def gather_nonblock(params):
            return params

    layers_per_stage = model_cfg.n_layer // n_stages

    def head_loss(params, y, targets):
        """Last-stage CE. With cfg.fused_head_ce the head matmul is fused
        into the loss (ops/losses.linear_cross_entropy) — the pipeline's
        last stage is exactly where the unfused [B, T, V] logits would be
        the step's largest activation (2.1 GB bf16 at llama-3 vocab)."""
        if model_cfg.fused_head_ce:
            hidden = model.final_norm(params, y, model_cfg)
            w, layout = model.head_weight(params)
            return linear_cross_entropy(
                hidden.reshape(-1, hidden.shape[-1]),
                w,
                targets.reshape(-1),
                w_layout=layout,
                logits_dtype=model_cfg.logits_dtype,
            )
        return cross_entropy_loss(
            model.head(params, y, model_cfg), targets
        )


    def forward_loss(params, inputs_mb, targets_mb, dropout_key):
        """Pipelined forward over all M microbatches; mean loss."""
        from pytorch_distributed_tpu.ops.layers import dropout as _dropout

        params = gather_nonblock(params)
        m = inputs_mb.shape[0]
        b, t = inputs_mb.shape[1], inputs_mb.shape[2]
        stage = jax.lax.axis_index("pipe")
        n_ticks = m + n_stages - 1
        if train_mode:
            dropout_key = fold_batch_shard_key(dropout_key, mesh_cfg)

        def tick(carry, tk):
            x_buf, loss_acc = carry
            in_idx = jnp.clip(tk, 0, m - 1)
            # Stage s processes microbatch tk - s this tick; its dropout
            # keys derive from that GLOBAL microbatch index (bubble ticks
            # reuse a clipped index on garbage — loss-gated, harmless).
            mb_idx = jnp.clip(tk - stage, 0, m - 1)
            if train_mode:
                key_blocks, k_embd = microbatch_keys(dropout_key, mb_idx)
            else:
                key_blocks = k_embd = None

            def embed_branch():
                x = model.embed(
                    params,
                    jax.lax.dynamic_index_in_dim(
                        inputs_mb, in_idx, 0, keepdims=False
                    ),
                    model_cfg,
                    seq_axis=seq_axis,
                )
                if train_mode:
                    x = _dropout(
                        x, model_cfg.embd_pdrop, k_embd,
                        deterministic=False,
                    )
                return x

            x_in = jax.lax.cond(stage == 0, embed_branch, lambda: x_buf)
            if model_cfg.n_experts:
                y, aux = model.run_blocks(
                    params["blocks"], x_in, model_cfg,
                    block_transform=gather_block, return_aux=True,
                    tensor_axis=tensor_axis, expert_axis=expert_axis,
                    seq_axis=seq_axis,
                    dropout_key=key_blocks, deterministic=not train_mode,
                    layer_offset=stage * layers_per_stage,
                )
                # Stage s computes on microbatch tk - s; bubble ticks run
                # on garbage whose router aux is nonzero — gate it out so
                # only real microbatches' load-balancing terms contribute.
                valid_mb = (tk - stage >= 0) & (tk - stage < m)
                aux_t = (
                    jnp.where(valid_mb, aux, 0.0).astype(jnp.float32)
                    * model_cfg.moe_aux_coef
                )
            else:
                y = model.run_blocks(
                    params["blocks"], x_in, model_cfg,
                    block_transform=gather_block,
                    tensor_axis=tensor_axis, seq_axis=seq_axis,
                    dropout_key=key_blocks, deterministic=not train_mode,
                    layer_offset=stage * layers_per_stage,
                )
                aux_t = 0.0
            out_idx = tk - (n_stages - 1)
            valid_out = (stage == n_stages - 1) & (out_idx >= 0)
            loss_t = jax.lax.cond(
                valid_out,
                lambda: head_loss(
                    params, y,
                    jax.lax.dynamic_index_in_dim(
                        targets_mb, jnp.clip(out_idx, 0, m - 1), 0,
                        keepdims=False,
                    ),
                ),
                lambda: _vary(jnp.zeros((), jnp.float32)),
            )
            x_next = jax.lax.ppermute(y, "pipe", perm)
            return (x_next, loss_acc + loss_t + aux_t), None

        x0 = _vary(
            jnp.zeros((b, t, model_cfg.n_embd), jnp.dtype(model_cfg.dtype))
        )
        (x_buf, loss_sum), _ = jax.lax.scan(
            tick,
            (x0, _vary(jnp.zeros((), jnp.float32))),
            jnp.arange(n_ticks),
        )
        # CE accumulated on the last stage; MoE aux terms on every stage —
        # the psum over pipe assembles the full loss and replicates it.
        return jax.lax.psum(loss_sum, "pipe") / m

    grad_fn = jax.value_and_grad(forward_loss)

    def loss_and_grads_1f1b(vparams, inputs_mb, targets_mb, dropout_key):
        """Hand-scheduled 1F1B (PipeDream-flush): stage s runs F(m) at tick
        2m+s and B(m) at tick 2m+2S-1-s. F and B land on opposite tick
        parities per stage (no conflict), every producer->consumer hop is
        exactly one tick, and at most S-s microbatch inputs are in flight
        on stage s — so the activation stash is S slots instead of GPipe's
        M. AD cannot express this interleaving (transposing the forward
        scan yields the backward as a SECOND full pass), so each B tick
        re-runs its stage forward under ``jax.vjp`` seeded with the
        cotangent arriving from the next stage (full-stage remat; ~1x
        extra stage compute is the price of the S/M activation-memory
        reduction)."""
        m = inputs_mb.shape[0]
        b, t = inputs_mb.shape[1], inputs_mb.shape[2]
        e = model_cfg.n_embd
        dt = jnp.dtype(model_cfg.dtype)
        stage = jax.lax.axis_index("pipe")
        n_ticks = 2 * (m + n_stages - 1)
        perm_bwd = [(i, i - 1) for i in range(1, n_stages)]
        if train_mode:
            dropout_key = fold_batch_shard_key(dropout_key, mesh_cfg)

        from pytorch_distributed_tpu.ops.layers import dropout as _dropout

        def stage_apply(params, x, tok, tgt, mb_idx):
            params = gather_nonblock(params)
            if train_mode:
                key_blocks, k_embd = microbatch_keys(dropout_key, mb_idx)
            else:
                key_blocks = k_embd = None

            def embed_branch():
                e = model.embed(params, tok, model_cfg, seq_axis=seq_axis)
                if train_mode:
                    e = _dropout(
                        e, model_cfg.embd_pdrop, k_embd,
                        deterministic=False,
                    )
                return e

            x0 = jax.lax.cond(stage == 0, embed_branch, lambda: x)
            if model_cfg.n_experts:
                # Per-stage local loss includes this stage's layers' aux
                # term; B ticks only ever run on real microbatches (is_b
                # gating below), so no bubble-garbage gate is needed here.
                y, aux = model.run_blocks(
                    params["blocks"], x0, model_cfg,
                    block_transform=gather_block, return_aux=True,
                    tensor_axis=tensor_axis, expert_axis=expert_axis,
                    seq_axis=seq_axis,
                    dropout_key=key_blocks, deterministic=not train_mode,
                    layer_offset=stage * layers_per_stage,
                )
                aux_t = aux.astype(jnp.float32) * model_cfg.moe_aux_coef
            else:
                y = model.run_blocks(
                    params["blocks"], x0, model_cfg,
                    block_transform=gather_block,
                    tensor_axis=tensor_axis, seq_axis=seq_axis,
                    dropout_key=key_blocks, deterministic=not train_mode,
                    layer_offset=stage * layers_per_stage,
                )
                aux_t = _vary(jnp.zeros((), jnp.float32))
            loss = jax.lax.cond(
                stage == n_stages - 1,
                lambda: head_loss(params, y, tgt),
                lambda: _vary(jnp.zeros((), jnp.float32)),
            )
            return y, loss + aux_t

        def mb_slices(idx):
            tok = jax.lax.dynamic_index_in_dim(
                inputs_mb, idx, 0, keepdims=False
            )
            tgt = jax.lax.dynamic_index_in_dim(
                targets_mb, idx, 0, keepdims=False
            )
            return tok, tgt

        zero_act = _vary(jnp.zeros((b, t, e), dt))
        zero_grads = jax.tree.map(
            lambda p: pvary_missing(
                jnp.zeros(p.shape, jnp.float32),
                tuple(vma_of(p)),
            ),
            vparams,
        )

        def tick(carry, tk):
            fwd_in, bwd_in, stash, gacc, lacc = carry

            # ---- forward op: F(s, m_f) at tk == 2*m_f + s ----------------
            mf2 = tk - stage
            is_f = (mf2 >= 0) & (mf2 % 2 == 0) & (mf2 < 2 * m)
            m_f = jnp.clip(mf2 // 2, 0, m - 1)
            tok_f, tgt_f = mb_slices(m_f)

            def do_f(stash):
                slot = jnp.mod(m_f, n_stages)
                stash = jax.lax.dynamic_update_slice_in_dim(
                    stash, fwd_in[None], slot, axis=0
                )
                y, _ = stage_apply(vparams, fwd_in, tok_f, tgt_f, m_f)
                return y, stash

            if seq_axis is None:
                y_out, stash = jax.lax.cond(
                    is_f, do_f, lambda st: (zero_act, st), stash
                )
            else:
                # Ring/ulysses collectives ride the "seq" axis, but
                # lax.ppermute lowers to a collective whose rendezvous
                # spans EVERY device — gating it behind a cond on the
                # pipe-varying schedule predicate deadlocks (or pairs
                # mismatched hops and exchanges garbage). With a seq axis
                # the stage body therefore runs UNCONDITIONALLY — every
                # device executes the same collective sequence every tick
                # — and the schedule gates the RESULTS: bubble ticks
                # compute on garbage that is discarded, exactly like the
                # GPipe loss gate.
                y_all, stash_all = do_f(stash)
                y_out = jnp.where(is_f, y_all, zero_act)
                stash = jnp.where(is_f, stash_all, stash)

            # ---- backward op: B(s, m_b) at tk == 2*m_b + 2S-1 - s --------
            mb2 = tk - (2 * n_stages - 1 - stage)
            is_b = (mb2 >= 0) & (mb2 % 2 == 0) & (mb2 < 2 * m)
            m_b = jnp.clip(mb2 // 2, 0, m - 1)
            tok_b, tgt_b = mb_slices(m_b)

            def do_b(operands):
                bwd_in, stash = operands
                x_saved = jax.lax.dynamic_index_in_dim(
                    stash, jnp.mod(m_b, n_stages), 0, keepdims=False
                )
                (y_p, loss_p), vjp = jax.vjp(
                    lambda p, x: stage_apply(p, x, tok_b, tgt_b, m_b),
                    vparams, x_saved,
                )
                # Seed: every stage differentiates its own mean-scaled
                # local loss (the CE term lives on the last stage; the MoE
                # aux term on every stage — for dense configs non-final
                # stages' loss is the constant 0 and the seed is inert);
                # non-final stages additionally chain the arriving
                # cotangent into y.
                dy = jnp.where(stage == n_stages - 1, 0.0, 1.0) * bwd_in
                dl = jnp.full((), 1.0 / m, jnp.float32)
                dp, dx = vjp((dy.astype(y_p.dtype), _vary(dl)))
                return dp, dx.astype(dt), loss_p

            if seq_axis is None:
                dp, dx_out, loss_p = jax.lax.cond(
                    is_b,
                    do_b,
                    lambda ops: (zero_grads, zero_act,
                                 _vary(jnp.zeros((), jnp.float32))),
                    (bwd_in, stash),
                )
            else:
                # Same uniform-collective contract as the forward op.
                dp_all, dx_all, loss_all = do_b((bwd_in, stash))
                dp = jax.tree.map(
                    lambda a, z: jnp.where(is_b, a, z), dp_all, zero_grads
                )
                dx_out = jnp.where(is_b, dx_all, zero_act)
                loss_p = jnp.where(
                    is_b, loss_all, _vary(jnp.zeros((), jnp.float32))
                )
            gacc = jax.tree.map(jnp.add, gacc, dp)
            lacc = lacc + loss_p

            # ---- neighbour exchange (consumed exactly one tick later) ----
            fwd_next = jax.lax.ppermute(y_out, "pipe", perm)
            bwd_next = jax.lax.ppermute(dx_out, "pipe", perm_bwd)
            return (fwd_next, bwd_next, stash, gacc, lacc), None

        stash0 = _vary(jnp.zeros((n_stages, b, t, e), dt))
        carry0 = (
            zero_act, zero_act, stash0, zero_grads,
            _vary(jnp.zeros((), jnp.float32)),
        )
        carry_out, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
        _, _, _, gacc, lacc = carry_out
        loss = jax.lax.psum(lacc, "pipe") / m
        return loss, gacc

    def step_impl(state: TrainState, batch: dict, dropout_key: jax.Array):
        vparams = jax.tree.map(_vary, state.params)
        if schedule == "1f1b":
            loss, grads = loss_and_grads_1f1b(
                vparams, batch["inputs"], batch["targets"], dropout_key
            )
        else:
            loss, grads = grad_fn(
                vparams, batch["inputs"], batch["targets"], dropout_key
            )

        # Replicated leaves hold disjoint per-stage partials — psum over
        # pipe reconstructs the full grad; pipe-sharded block leaves are
        # already exact.
        grads = jax.tree.map(
            lambda g, spec: (
                g if _has_pipe(spec) else jax.lax.psum(g, "pipe")
            ),
            grads,
            specs.params,
        )
        if expert_axis is not None:
            grads = jax.tree.map(
                lambda g, spec: (
                    g / mesh_cfg.expert
                    if _has_axis(spec, "expert")
                    else jax.lax.pmean(g, expert_axis)
                ),
                grads,
                specs.params,
            )
            loss = jax.lax.pmean(loss, expert_axis)
        if fsdp_size > 1:
            if strategy == "full_shard":
                # fsdp-sharded leaves: the gather's AD transpose SUMMED the
                # per-shard grads over fsdp (reduce-scatter) — normalise to
                # a mean; leaves with no fsdp dim are per-shard partials
                # over the fsdp batch slice — a real pmean.
                grads = jax.tree.map(
                    lambda g, spec: (
                        g / fsdp_size
                        if _has_axis(spec, "fsdp")
                        else jax.lax.pmean(g, "fsdp")
                    ),
                    grads,
                    specs.params,
                )
            elif strategy == "shard_grad_op":
                # In-stage ZeRO-2: params stayed replicated over fsdp in
                # compute, so grads are per-shard batch partials —
                # reduce-scatter them to fsdp shards (+ normalise the sum
                # to a mean). The update below runs on the shards.
                grads = scatter_grads(grads, shard_param_specs, fsdp_size)
                grads = jax.tree.map(lambda g: g / fsdp_size, grads)
            else:
                # ZeRO-1 / no_shard: plain DDP all-reduce(AVG) over fsdp.
                grads = jax.lax.pmean(grads, "fsdp")
            loss = jax.lax.pmean(loss, "fsdp")
        if seq_axis is not None:
            # Context parallelism: params are replicated over seq; each
            # shard computed grads of its local-token mean loss — the
            # global mean of both is the seq-average (same convention as
            # parallel/explicit.py).
            grads = jax.lax.pmean(grads, seq_axis)
            loss = jax.lax.pmean(loss, seq_axis)
        if data_axis:
            grads = jax.lax.pmean(grads, data_axis)
            loss = jax.lax.pmean(loss, data_axis)

        # Per-leaf squared sums psum'd over exactly the axes the leaf is
        # sharded over (pipe and/or fsdp); replicated leaves unsummed.
        # Computed BEFORE the update so it can drive clipping. Under
        # ZeRO-2 the grads were just reduce-scattered, so the fsdp-psum
        # axes come from the SHARD layout, not the (replicated) param
        # layout.
        norm_specs = (
            shard_param_specs
            if strategy == "shard_grad_op" and fsdp_size > 1
            else specs.params
        )
        buckets: dict = {}
        for g, spec in zip(
            jax.tree.leaves(grads),
            jax.tree.leaves(
                norm_specs, is_leaf=lambda x: isinstance(x, P)
            ),
        ):
            axes = tuple(
                ax for ax in ("pipe", "fsdp", "tensor", "expert")
                if _has_axis(spec, ax)
                and (ax != "fsdp" or fsdp_size > 1)
                and (ax != "tensor" or tensor_axis is not None)
                and (ax != "expert" or expert_axis is not None)
            )
            buckets[axes] = buckets.get(axes, 0.0) + jnp.sum(
                jnp.square(g.astype(jnp.float32))
            )
        sq = jnp.zeros((), jnp.float32)
        for axes, val in buckets.items():
            for ax in axes:
                val = jax.lax.psum(val, ax)
            sq = sq + val
        grad_norm = jnp.sqrt(sq)

        if grad_clip_norm is not None:
            # Shared typed global-norm clip (parallel/zero.py) — the SAME
            # helper the explicit path uses, so clip semantics cannot
            # diverge between the two shard_map paths.
            grads = clip_by_global_norm_typed(grads, grad_norm, grad_clip_norm)

        if strategy in ("shard_grad_op", "shard_opt") and fsdp_size > 1:
            # ZeRO-2 / ZeRO-1 sharded update + re-materialise on the
            # pipe-local param slices (parallel/zero.py — shared with the
            # explicit path).
            new_params, new_opt_state = zero_sharded_update(
                tx, state.params, state.opt_state, grads,
                shard_param_specs, fsdp_size, strategy,
            )
        else:
            updates, new_opt_state = tx.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)

        metrics = {"loss": loss, "grad_norm": grad_norm}
        return TrainState(new_params, new_opt_state, state.step + 1), metrics

    smapped = shard_map(
        step_impl,
        mesh=mesh,
        in_specs=(
            specs,
            {"inputs": batch_spec, "targets": batch_spec},
            P(),
        ),
        out_specs=(specs, {"loss": P(), "grad_norm": P()}),
        check_vma=True,
    )
    return jax.jit(smapped, donate_argnums=(0,))


def _has_pipe(spec: P) -> bool:
    return _has_axis(spec, "pipe")


def microbatch_keys(dropout_key: jax.Array, mb_idx):
    """(block_key, embd_key) for one microbatch — the SAME fold/split
    sequence the single-device step + apply() perform (fold per accum
    index, split off the embd key), so pipe-only meshes reproduce its
    masks bitwise."""
    key_mb = jax.random.fold_in(dropout_key, mb_idx)
    return jax.random.split(key_mb)
