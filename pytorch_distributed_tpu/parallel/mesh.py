"""Device-mesh construction and process identity.

TPU-native replacement for the reference's process-group setup
(reference train_ddp.py:23-36: init_process_group('nccl') + RANK/WORLD_SIZE/
LOCAL_RANK env vars + cuda.set_device): here the runtime is
``jax.distributed.initialize()`` (multi-host) plus a ``jax.sharding.Mesh``
over the device slice; identity is ``jax.process_index()/process_count()``;
there is no teardown (reference train_ddp.py:146's destroy_process_group has
no analogue — XLA owns the channel lifetime).

Mesh axes (MeshConfig.axis_order): data / fsdp / seq / tensor. Collectives
ride ICI within a slice, DCN across slices; putting "data" outermost keeps
the highest-volume gradient reductions on the fastest links when XLA lays
device coordinates out innermost-last.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.config import MeshConfig


def initialize_distributed() -> None:
    """Multi-host rendezvous (idempotent). On a single-process TPU or CPU
    testbed this is a no-op; on a pod each host calls it once before any
    devices are used (the torchrun-rendezvous analogue)."""
    if jax.process_count() > 1:
        return  # already initialised by the launcher
    try:
        jax.distributed.initialize()
    except (ValueError, RuntimeError):
        # Single-process: no coordinator configured — fine.
        pass


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def make_mesh(cfg: MeshConfig, devices=None) -> Mesh:
    """Build a Mesh of shape cfg.shape over the given (or all) devices.

    When ``cfg.device_ids`` is set and no explicit ``devices`` override
    is passed, the mesh is built over exactly those process-local
    device ids, in order — the placement hook that lets a serving fleet
    give each replica its own disjoint slice of the machine."""
    if devices is None:
        if cfg.device_ids is not None:
            by_id = {d.id: d for d in jax.devices()}
            missing = [i for i in cfg.device_ids if i not in by_id]
            if missing:
                raise ValueError(
                    f"device_ids {missing} not present among "
                    f"jax.devices() ids {sorted(by_id)}"
                )
            devices = [by_id[i] for i in cfg.device_ids]
        else:
            devices = jax.devices()
    n = cfg.num_devices
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices ({cfg.shape}) but only "
            f"{len(devices)} available"
        )
    shape = tuple(cfg.shape.values())
    try:
        arr = mesh_utils.create_device_mesh(
            shape, devices=list(devices)[:n]
        )
    except (ValueError, NotImplementedError, AssertionError):
        # Non-TPU topologies (CPU test meshes): plain reshape is fine.
        arr = np.array(list(devices)[:n]).reshape(shape)
    return Mesh(arr, axis_names=cfg.axis_order)


def fold_batch_shard_key(dropout_key, mesh_cfg: MeshConfig):
    """Per-shard dropout key (must be called inside shard_map) — the ONE
    convention both shard_map training paths use. Independent masks per
    batch/sequence shard: the replicated key would give row i of every
    shard the SAME mask — correlated in a way single-device training
    never is — so each sharded batch axis's index is folded in (round-5
    fix, VERDICT r4 weak #6). The pipe axis is NOT folded — all pipeline
    stages must derive one mask stream per microbatch so pipe-only meshes
    stay bitwise-equal to the single-device step — and neither is tensor
    (replicated activations; attention dropout under TP has its own
    folded-key opt-in, models/gpt2.py)."""
    import jax

    for ax in ("data", "fsdp", "expert", "seq"):
        if getattr(mesh_cfg, ax) > 1:
            dropout_key = jax.random.fold_in(
                dropout_key, jax.lax.axis_index(ax)
            )
    return dropout_key


def batch_partition_spec(cfg: MeshConfig) -> P:
    """Global-batch sharding: batch dim split over data AND fsdp axes (FSDP
    is data parallelism with sharded state — each fsdp shard still consumes
    its own slice of the batch) AND the expert axis (expert parallelism
    shards tokens too; all_to_all moves them to their expert's owner);
    sequence dim split over seq for context parallelism. [A, B, T] batches
    shard B and T."""
    batch_axes = tuple(
        ax for ax in ("data", "fsdp", "expert") if getattr(cfg, ax) > 1
    ) or None
    seq_axis = "seq" if cfg.seq > 1 else None
    return P(None, batch_axes, seq_axis)


def make_batch_put(mesh: Mesh, cfg: MeshConfig):
    """Returns a function placing a host {inputs, targets} batch of [A, B, T]
    arrays onto the mesh with the batch sharding (single source of truth for
    batch placement — used by the pjit path, the explicit path, and entry
    scripts)."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, batch_partition_spec(cfg))

    def put(batch: dict) -> dict:
        return {
            k: jax.device_put(np.asarray(v), sharding)
            for k, v in batch.items()
        }

    return put


def data_parallel_size(cfg: MeshConfig) -> int:
    """How many ways the batch is split (the 'world size' in the reference's
    grad-accum rule, distributed_trainer.py:84-88)."""
    return cfg.data * cfg.fsdp * cfg.expert
