"""Shared ZeRO building blocks for the hand-written (shard_map) paths.

The explicit DP/FSDP path (parallel/explicit.py) and the pipeline path
(parallel/pipeline.py) implement the same ZeRO ladder over the "fsdp"
axis; the pieces that must stay numerically identical between them live
here once:

- per-leaf fsdp gather / reduce-scatter / slice / re-materialise
  primitives (ring-collective FSDP algebra);
- the typed global-norm gradient clip (optax.clip_by_global_norm
  semantics against an ALREADY-psum'd global norm — every shard applies
  the same scale);
- the ZeRO-2/ZeRO-1 sharded Adam update + param re-materialisation.

All functions run INSIDE shard_map under check_vma typing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.ops.tp import pvary_missing


def axis_dim(spec: P, axis: str = "fsdp") -> int | None:
    """Dim index the named mesh axis shards in this spec (specs may carry
    several axes — e.g. fsdp AND tensor — so the dim must be looked up by
    name, not 'first sharded')."""
    for i, entry in enumerate(spec):
        if entry == axis or (isinstance(entry, tuple) and axis in entry):
            return i
    return None


def spec_has(spec: P, axis: str) -> bool:
    return axis_dim(spec, axis) is not None


def gather_params(params, specs):
    """all_gather each fsdp-sharded leaf along its fsdp dim (tiled)."""

    def gather(leaf, spec):
        dim = axis_dim(spec, "fsdp")
        if dim is None:
            return leaf
        return jax.lax.all_gather(leaf, "fsdp", axis=dim, tiled=True)

    return jax.tree.map(gather, params, specs)


def scatter_grads(grads, specs, fsdp_size: int):
    """psum_scatter each leaf along its fsdp dim; leaves with no fsdp dim
    get a plain psum. Produces the *sum* over the fsdp axis."""

    def scatter(leaf, spec):
        dim = axis_dim(spec, "fsdp")
        if dim is None:
            return jax.lax.psum(leaf, "fsdp")
        return jax.lax.psum_scatter(
            leaf, "fsdp", scatter_dimension=dim, tiled=True
        )

    return jax.tree.map(scatter, grads, specs)


def scatter_grads_bucketed(grads, specs, fsdp_size: int, n_buckets: int):
    """``scatter_grads`` with the per-leaf psum_scatters coalesced into
    ~``n_buckets`` bucketed collectives (reference: the DDP C++ reducer's
    gradient bucketing, here applied to ZeRO-2's boundary reduce-scatter).

    Each fsdp-sharded leaf is rearranged so its fsdp dim leads, reshaped
    to [fsdp_size, -1], and concatenated with its bucket-mates; ONE
    psum_scatter per bucket then reduces+splits the whole bucket, and the
    shards are sliced back out. Fewer, larger transfers amortise the
    per-collective latency and give XLA's scheduler independent buckets
    to pipeline. Numerically identical to ``scatter_grads``: the same
    elementwise sums over the same chunk of each leaf, just transported
    together (equivalence pinned in tests/test_prefetch.py).

    Buckets are formed within (dtype, vma) groups — mixed-dtype grads
    (bf16 accumulation) and mixed-vma leaves (tensor-sharded vs
    replicated under TP x ZeRO-2) cannot share a concatenation. Leaves
    with no fsdp dim keep their plain psum, exactly like
    ``scatter_grads``."""
    from pytorch_distributed_tpu.utils.compat import vma_of

    leaves, treedef = jax.tree.flatten(grads)
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    dims = [axis_dim(spec, "fsdp") for spec in spec_leaves]
    out: list = [None] * len(leaves)

    groups: dict[tuple, list[int]] = {}
    for i, (leaf, dim) in enumerate(zip(leaves, dims)):
        if dim is None:
            out[i] = jax.lax.psum(leaf, "fsdp")
        else:
            key = (str(leaf.dtype), tuple(sorted(vma_of(leaf))))
            groups.setdefault(key, []).append(i)

    for idxs in groups.values():
        total = sum(leaves[i].size for i in idxs)
        target = -(-total // max(1, n_buckets))  # ceil
        buckets: list[list[int]] = [[]]
        filled = 0
        for i in idxs:
            if filled >= target and buckets[-1]:
                buckets.append([])
                filled = 0
            buckets[-1].append(i)
            filled += leaves[i].size
        for bucket in buckets:
            parts, metas = [], []
            for i in bucket:
                g, dim = leaves[i], dims[i]
                moved = jnp.moveaxis(g, dim, 0)
                parts.append(moved.reshape(fsdp_size, -1))
                metas.append((i, moved.shape, dim))
            flat = (
                parts[0]
                if len(parts) == 1
                else jnp.concatenate(parts, axis=1)
            )
            scattered = jax.lax.psum_scatter(
                flat, "fsdp", scatter_dimension=0, tiled=True
            )  # [1, total/fsdp_size]: this shard's chunk of the bucket sum
            off = 0
            for i, moved_shape, dim in metas:
                width = leaves[i].size // fsdp_size
                shard_shape = (
                    moved_shape[0] // fsdp_size,
                ) + moved_shape[1:]
                piece = scattered[:, off:off + width].reshape(shard_shape)
                out[i] = jnp.moveaxis(piece, 0, dim)
                off += width

    return jax.tree.unflatten(treedef, out)


def shard_slice(full, spec: P, fsdp_size: int):
    """Take this device's fsdp slice of a replicated array (ZeRO-2/1
    update)."""
    dim = axis_dim(spec, "fsdp")
    if dim is None:
        return full
    idx = jax.lax.axis_index("fsdp")
    size = full.shape[dim] // fsdp_size
    return jax.lax.dynamic_slice_in_dim(full, idx * size, size, axis=dim)


def unscatter(shard, full_like, spec: P):
    """Rebuild the full replicated array from disjoint per-device shards
    (inverse of ``shard_slice``): pad to full size at this device's slice
    and psum over "fsdp". Numerically identical to all_gather of the
    shards, but typed INVARIANT over fsdp by the varying-manual-axes
    system — all_gather output stays typed varying, which would fail
    replicated out_specs under check_vma. (Bandwidth 2x an all_gather;
    the teaching path trades that for a machine-checked replication
    invariant.)"""
    dim = axis_dim(spec, "fsdp")
    if dim is None:
        return shard
    idx = jax.lax.axis_index("fsdp")
    size = shard.shape[dim]
    padded = jax.lax.dynamic_update_slice_in_dim(
        jnp.zeros(full_like.shape, shard.dtype), shard, idx * size, axis=dim
    )
    return jax.lax.psum(padded, "fsdp")


def clip_by_global_norm_typed(grads, grad_norm, clip_norm: float):
    """optax.clip_by_global_norm semantics against the GLOBAL norm:
    identity when under the threshold, uniform (g/norm)*max scale when
    over — the same scale on every shard. ``grad_norm`` must already be
    the psum'd global norm (invariant); it is pcast up to each leaf's vma
    before mixing."""

    def clip_leaf(g):
        gn = pvary_missing(
            grad_norm, tuple(getattr(g.aval, "vma", frozenset()))
        )
        return jnp.where(gn < clip_norm, g, (g / gn) * clip_norm)

    return jax.tree.map(clip_leaf, grads)


def zero_sharded_update(
    tx: optax.GradientTransformation,
    params,
    opt_state,
    grads,
    shard_specs,
    fsdp_size: int,
    strategy: str,
):
    """ZeRO-2 / ZeRO-1 shared machinery: sharded Adam update on this
    device's fsdp slice of the (replicated-in-compute) params against the
    sharded optimizer state, then re-materialise full params.

    The two levels differ only in what arrives here: "shard_grad_op"
    grads were reduce-scattered by the caller (already sharded in the
    ``shard_specs`` layout); "shard_opt" grads stayed replicated
    (all-reduced) and are sliced now. Returns (new_params,
    new_opt_state)."""
    params_shard = jax.tree.map(
        lambda p, spec: shard_slice(p, spec, fsdp_size), params, shard_specs
    )
    grads_for_update = (
        grads
        if strategy == "shard_grad_op"
        else jax.tree.map(
            lambda g, spec: shard_slice(g, spec, fsdp_size),
            grads,
            shard_specs,
        )
    )
    updates, new_opt_state = tx.update(
        grads_for_update, opt_state, params_shard
    )
    new_params_shard = optax.apply_updates(params_shard, updates)
    new_params = jax.tree.map(
        lambda s, full, spec: unscatter(s, full, spec),
        new_params_shard, params, shard_specs,
    )
    return new_params, new_opt_state
