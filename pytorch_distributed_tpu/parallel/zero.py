"""Shared ZeRO building blocks for the hand-written (shard_map) paths.

The explicit DP/FSDP path (parallel/explicit.py) and the pipeline path
(parallel/pipeline.py) implement the same ZeRO ladder over the "fsdp"
axis; the pieces that must stay numerically identical between them live
here once:

- per-leaf fsdp gather / reduce-scatter / slice / re-materialise
  primitives (ring-collective FSDP algebra);
- the typed global-norm gradient clip (optax.clip_by_global_norm
  semantics against an ALREADY-psum'd global norm — every shard applies
  the same scale);
- the ZeRO-2/ZeRO-1 sharded Adam update + param re-materialisation.

All functions run INSIDE shard_map under check_vma typing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.ops.tp import pvary_missing


def axis_dim(spec: P, axis: str = "fsdp") -> int | None:
    """Dim index the named mesh axis shards in this spec (specs may carry
    several axes — e.g. fsdp AND tensor — so the dim must be looked up by
    name, not 'first sharded')."""
    for i, entry in enumerate(spec):
        if entry == axis or (isinstance(entry, tuple) and axis in entry):
            return i
    return None


def spec_has(spec: P, axis: str) -> bool:
    return axis_dim(spec, axis) is not None


def gather_params(params, specs):
    """all_gather each fsdp-sharded leaf along its fsdp dim (tiled)."""

    def gather(leaf, spec):
        dim = axis_dim(spec, "fsdp")
        if dim is None:
            return leaf
        return jax.lax.all_gather(leaf, "fsdp", axis=dim, tiled=True)

    return jax.tree.map(gather, params, specs)


def scatter_grads(grads, specs, fsdp_size: int):
    """psum_scatter each leaf along its fsdp dim; leaves with no fsdp dim
    get a plain psum. Produces the *sum* over the fsdp axis."""

    def scatter(leaf, spec):
        dim = axis_dim(spec, "fsdp")
        if dim is None:
            return jax.lax.psum(leaf, "fsdp")
        return jax.lax.psum_scatter(
            leaf, "fsdp", scatter_dimension=dim, tiled=True
        )

    return jax.tree.map(scatter, grads, specs)


def shard_slice(full, spec: P, fsdp_size: int):
    """Take this device's fsdp slice of a replicated array (ZeRO-2/1
    update)."""
    dim = axis_dim(spec, "fsdp")
    if dim is None:
        return full
    idx = jax.lax.axis_index("fsdp")
    size = full.shape[dim] // fsdp_size
    return jax.lax.dynamic_slice_in_dim(full, idx * size, size, axis=dim)


def unscatter(shard, full_like, spec: P):
    """Rebuild the full replicated array from disjoint per-device shards
    (inverse of ``shard_slice``): pad to full size at this device's slice
    and psum over "fsdp". Numerically identical to all_gather of the
    shards, but typed INVARIANT over fsdp by the varying-manual-axes
    system — all_gather output stays typed varying, which would fail
    replicated out_specs under check_vma. (Bandwidth 2x an all_gather;
    the teaching path trades that for a machine-checked replication
    invariant.)"""
    dim = axis_dim(spec, "fsdp")
    if dim is None:
        return shard
    idx = jax.lax.axis_index("fsdp")
    size = shard.shape[dim]
    padded = jax.lax.dynamic_update_slice_in_dim(
        jnp.zeros(full_like.shape, shard.dtype), shard, idx * size, axis=dim
    )
    return jax.lax.psum(padded, "fsdp")


def clip_by_global_norm_typed(grads, grad_norm, clip_norm: float):
    """optax.clip_by_global_norm semantics against the GLOBAL norm:
    identity when under the threshold, uniform (g/norm)*max scale when
    over — the same scale on every shard. ``grad_norm`` must already be
    the psum'd global norm (invariant); it is pcast up to each leaf's vma
    before mixing."""

    def clip_leaf(g):
        gn = pvary_missing(
            grad_norm, tuple(getattr(g.aval, "vma", frozenset()))
        )
        return jnp.where(gn < clip_norm, g, (g / gn) * clip_norm)

    return jax.tree.map(clip_leaf, grads)


def zero_sharded_update(
    tx: optax.GradientTransformation,
    params,
    opt_state,
    grads,
    shard_specs,
    fsdp_size: int,
    strategy: str,
):
    """ZeRO-2 / ZeRO-1 shared machinery: sharded Adam update on this
    device's fsdp slice of the (replicated-in-compute) params against the
    sharded optimizer state, then re-materialise full params.

    The two levels differ only in what arrives here: "shard_grad_op"
    grads were reduce-scattered by the caller (already sharded in the
    ``shard_specs`` layout); "shard_opt" grads stayed replicated
    (all-reduced) and are sliced now. Returns (new_params,
    new_opt_state)."""
    params_shard = jax.tree.map(
        lambda p, spec: shard_slice(p, spec, fsdp_size), params, shard_specs
    )
    grads_for_update = (
        grads
        if strategy == "shard_grad_op"
        else jax.tree.map(
            lambda g, spec: shard_slice(g, spec, fsdp_size),
            grads,
            shard_specs,
        )
    )
    updates, new_opt_state = tx.update(
        grads_for_update, opt_state, params_shard
    )
    new_params_shard = optax.apply_updates(params_shard, updates)
    new_params = jax.tree.map(
        lambda s, full, spec: unscatter(s, full, spec),
        new_params_shard, params, shard_specs,
    )
    return new_params, new_opt_state
