"""Parameter/optimizer-state sharding rules — the FSDP strategy table.

Capability twin of the reference's three FSDP sharding strategies
(reference train_fsdp.py:49-59):

  full_shard     (ZeRO-3): params + grads + optimizer state sharded.
                 XLA inserts all_gather before use and reduce_scatter on
                 grads — exactly the collectives FSDP issues per wrapped
                 block (reference :50-52), but placed by the SPMD
                 partitioner instead of module hooks.
  shard_grad_op  (ZeRO-2): params replicated; optimizer state sharded.
                 The weight update runs on shards and re-gathers params —
                 reduce_scatter(grads) + sharded update + all_gather(params).
  shard_opt      (ZeRO-1, a level torch FSDP lacks): optimizer state
                 sharded only; grads all-reduce replicated, each shard
                 updates its slice, updated params re-gathered.
  no_shard       (DDP): everything replicated; gradient psum only.

Sharding is expressed per-leaf as a NamedSharding over the mesh's "fsdp"
axis: the largest dimension divisible by the axis size is sharded (prefer
the trailing — usually feature — dim on ties, which keeps the contracting
dim intact for the MXU). Stacked-block leaves [L, ...] therefore shard a
weight dim, not L, so scan-over-layers slices stay local.

Per-block granularity in the reference (wrap each transformer.h[i],
train_fsdp.py:71-81) maps to scan-over-layers + remat here: only one
layer's gathered params are live at a time.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.config import MeshConfig
from pytorch_distributed_tpu.train.state import TrainState

# Megatron-style tensor-parallel placement, keyed by param-path suffix.
# Dim indices are for the STACKED [L, in, out] (kernel) / [L, out] (bias)
# block leaves. Column-parallel layers (QKV / up-projections) shard the
# output dim; the following row-parallel projection shards its input dim, so
# the only forward collective is one psum after c_proj/wo/down — XLA's SPMD
# partitioner places it from these specs alone.
_TENSOR_RULES: dict[tuple[str, ...], int] = {
    # gpt2 (models/gpt2.py layout). The merged QKV kernel [L, E, 3, H, D]
    # shards its HEAD dim (3) — head-aligned, so q/k/v slicing and attention
    # run fully local (a flat-3E split would cross q/k/v boundaries and
    # compile to collective-permutes between c_attn and attention).
    ("attn", "c_attn", "kernel"): 3,
    ("attn", "c_attn", "bias"): 2,
    ("attn", "c_proj", "kernel"): 1,
    ("mlp", "c_fc", "kernel"): 2,
    ("mlp", "c_fc", "bias"): 1,
    ("mlp", "c_proj", "kernel"): 1,
    # llama (models/llama.py layout)
    ("attn", "wq"): 2,
    ("attn", "wk"): 2,
    ("attn", "wv"): 2,
    ("attn", "wo"): 1,
    ("mlp", "gate"): 2,
    ("mlp", "up"): 2,
    ("mlp", "down"): 1,
    # MoE expert FFNs (EP x TP): stacked [L, X, D, F] / [L, X, F, D] leaves
    # run Megatron TP INSIDE each expert — w_in/w_gate column-parallel on
    # the hidden dim F, w_out row-parallel on F (ops/moe.py
    # _expert_compute's tp_copy/tp_reduce pair). The router stays
    # replicated (routing must agree across tensor shards). Composes with
    # the "expert" dim-1 sharding below.
    ("mlp", "w_in"): 3,
    ("mlp", "w_gate"): 3,
    ("mlp", "w_out"): 2,
}
_TENSOR_SUFFIX_LENS = (3, 2)

# Expert-parallel placement: stacked MoE leaves [L, X, ...] shard their
# expert dim over the "expert" axis; the router stays replicated.
_EXPERT_RULES: dict[tuple[str, ...], int] = {
    ("mlp", "w_in"): 1,
    ("mlp", "w_gate"): 1,
    ("mlp", "w_out"): 1,
}


def _path_keys(path) -> tuple[str, ...]:
    """String keys of a jax tree path (non-string entries like list indices
    in optimizer state become their repr, which never matches a rule)."""
    return tuple(
        getattr(p, "key", None) if isinstance(getattr(p, "key", None), str)
        else str(p)
        for p in path
    )


def _tensor_dim(path) -> int | None:
    keys = _path_keys(path)
    for n in _TENSOR_SUFFIX_LENS:
        if len(keys) >= n and keys[-n:] in _TENSOR_RULES:
            return _TENSOR_RULES[keys[-n:]]
    return None


def _leaf_spec(
    shape: tuple[int, ...],
    mesh_cfg: MeshConfig,
    *,
    path,
    shard_fsdp: bool,
    min_dim: int = 0,
) -> P:
    """Combined tensor + fsdp spec for one leaf: the tensor rule (if any)
    claims its dim, then fsdp shards the largest remaining divisible dim
    >= min_dim (ties -> last dim)."""
    if not shape:
        return P()
    spec: list = [None] * len(shape)

    tdim = _tensor_dim(path) if mesh_cfg.tensor > 1 else None
    if tdim is not None:
        if shape[tdim] % mesh_cfg.tensor != 0:
            # Silent fallback would replicate this leaf tensor-ways — an
            # invisible memory regression at scale. Refuse instead.
            raise ValueError(
                f"tensor-parallel dim {tdim} of param "
                f"{'/'.join(_path_keys(path))} (shape {shape}) is not "
                f"divisible by tensor={mesh_cfg.tensor}"
            )
        spec[tdim] = "tensor"

    if mesh_cfg.expert > 1:
        keys = _path_keys(path)
        edim = _EXPERT_RULES.get(keys[-2:])
        if edim is not None:
            if shape[edim] % mesh_cfg.expert != 0:
                raise ValueError(
                    f"expert dim {edim} of param "
                    f"{'/'.join(keys)} (shape {shape}) is not divisible "
                    f"by expert={mesh_cfg.expert}"
                )
            spec[edim] = "expert"

    if shard_fsdp and mesh_cfg.fsdp > 1:
        best_dim, best_size = None, 0
        for i, s in enumerate(shape):
            if (
                i >= min_dim
                and spec[i] is None
                and s % mesh_cfg.fsdp == 0
                and s >= best_size
                and s > 1
            ):
                best_dim, best_size = i, s
        if best_dim is not None:
            spec[best_dim] = "fsdp"

    if all(ax is None for ax in spec):
        return P()
    return P(*spec)


def param_partition_specs(params, mesh_cfg: MeshConfig, *, for_grads=False):
    """PartitionSpec pytree for model params under the configured strategy.

    Tensor-parallel sharding (the "tensor" axis) applies under every FSDP
    strategy — TP is orthogonal to the ZeRO level. FSDP sharding of params
    applies only under full_shard.

    ``for_grads=True`` returns the specs for the GRADIENT pytree instead:
    gradients are fsdp-sharded under shard_grad_op too (ZeRO-2
    reduce-scatters grads onto the shards that own the optimizer state,
    while params stay replicated).

    Leaves under a top-level "blocks" key are layer-stacked [L, ...]; their
    leading dim is never sharded so scan-over-layers slices stay local and
    per-layer gathers (explicit FSDP) keep working.
    """
    if for_grads:
        shard_fsdp = mesh_cfg.strategy in ("full_shard", "shard_grad_op")
    else:
        shard_fsdp = mesh_cfg.strategy == "full_shard"

    def spec_for(path, leaf):
        keys = _path_keys(path)
        stacked = bool(keys) and keys[0] == "blocks"
        # Embedding tables ([V, E] wte / [C, E] wpe / [E, V] lm_head) shard
        # the embedding dim only: vocab-sharding the tied wte makes the
        # cross-entropy backward reshard batch-sharded dlogits to
        # vocab-sharded (an all-to-all SPMD degrades to full
        # rematerialisation), and vocab-parallel loss machinery is out of
        # scope. min_dim=1 skips dim 0 (for lm_head [E, V] dim 1 IS E-free —
        # but llama's untied head tolerates vocab sharding; keep it simple
        # and uniform).
        embedding = bool(keys) and keys[-1] in ("wte", "wpe")
        return _leaf_spec(
            tuple(leaf.shape),
            mesh_cfg,
            path=path,
            shard_fsdp=shard_fsdp,
            min_dim=1 if (stacked or embedding) else 0,
        )

    return jax.tree_util.tree_map_with_path(spec_for, params)


def opt_state_partition_specs(opt_state, params_specs, mesh_cfg: MeshConfig):
    """Optimizer-state sharding. Adam moments mirror the params tree shape;
    for full_shard they follow the param specs, for shard_grad_op they are
    fsdp-sharded even though params are replicated (ZeRO-2), for no_shard
    fsdp-replicated. Tensor-parallel dims always mirror the params (moments
    live where their params live). Scalar leaves (step counts) replicate."""
    del params_specs  # moments share param shapes; specs derive from shapes
    shard_fsdp = mesh_cfg.strategy in (
        "full_shard", "shard_grad_op", "shard_opt"
    )

    def leaf_spec(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape:
            return P()
        keys = _path_keys(path)
        stacked = "blocks" in keys
        # Moments mirror their params: embedding tables shard dim 1 only
        # (see param_partition_specs).
        embedding = bool(keys) and keys[-1] in ("wte", "wpe")
        return _leaf_spec(
            shape,
            mesh_cfg,
            path=path,
            shard_fsdp=shard_fsdp,
            min_dim=1 if (stacked or embedding) else 0,
        )

    return jax.tree_util.tree_map_with_path(leaf_spec, opt_state)


def state_shardings(state: TrainState, mesh: Mesh, mesh_cfg: MeshConfig):
    """NamedSharding pytree matching a TrainState."""
    p_specs = param_partition_specs(state.params, mesh_cfg)
    o_specs = opt_state_partition_specs(state.opt_state, p_specs, mesh_cfg)

    def to_sharding(spec):
        return NamedSharding(mesh, spec)

    return TrainState(
        params=jax.tree.map(to_sharding, p_specs),
        opt_state=jax.tree.map(to_sharding, o_specs),
        step=NamedSharding(mesh, P()),
        # Guard counters (train/guard.GuardState) are a few replicated
        # scalars; None (guard off) is an empty subtree and maps to None.
        guard=jax.tree.map(
            lambda _: NamedSharding(mesh, P()), state.guard
        ),
    )


def shard_train_state(
    state: TrainState, mesh: Mesh, mesh_cfg: MeshConfig
) -> tuple[TrainState, TrainState]:
    """Place a host/replicated TrainState onto the mesh per the strategy.

    Returns (sharded_state, shardings). This is the moment FSDP 'wraps' the
    model in the reference (train_fsdp.py:64-81) — here it is just a
    device_put with sharding annotations; XLA does the rest.
    """
    shardings = state_shardings(state, mesh, mesh_cfg)
    sharded = jax.device_put(state, shardings)
    return sharded, shardings
