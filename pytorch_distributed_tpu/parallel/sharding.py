"""Parameter/optimizer-state sharding rules — the FSDP strategy table.

Capability twin of the reference's three FSDP sharding strategies
(reference train_fsdp.py:49-59):

  full_shard     (ZeRO-3): params + grads + optimizer state sharded.
                 XLA inserts all_gather before use and reduce_scatter on
                 grads — exactly the collectives FSDP issues per wrapped
                 block (reference :50-52), but placed by the SPMD
                 partitioner instead of module hooks.
  shard_grad_op  (ZeRO-2): params replicated; optimizer state sharded.
                 The weight update runs on shards and re-gathers params —
                 reduce_scatter(grads) + sharded update + all_gather(params).
  no_shard       (DDP): everything replicated; gradient psum only.

Sharding is expressed per-leaf as a NamedSharding over the mesh's "fsdp"
axis: the largest dimension divisible by the axis size is sharded (prefer
the trailing — usually feature — dim on ties, which keeps the contracting
dim intact for the MXU). Stacked-block leaves [L, ...] therefore shard a
weight dim, not L, so scan-over-layers slices stay local.

Per-block granularity in the reference (wrap each transformer.h[i],
train_fsdp.py:71-81) maps to scan-over-layers + remat here: only one
layer's gathered params are live at a time.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_tpu.config import MeshConfig
from pytorch_distributed_tpu.train.state import TrainState


def _leaf_spec(
    shape: tuple[int, ...],
    axis_size: int,
    axis_name: str,
    *,
    min_dim: int = 0,
) -> P:
    """Shard the largest divisible dim >= min_dim along axis_name
    (ties -> last dim)."""
    if axis_size == 1 or not shape:
        return P()
    best_dim, best_size = None, 0
    for i, s in enumerate(shape):
        if i >= min_dim and s % axis_size == 0 and s >= best_size and s > 1:
            best_dim, best_size = i, s
    if best_dim is None:
        return P()  # small leaf (e.g. scalars, LN vectors) — replicate
    spec = [None] * len(shape)
    spec[best_dim] = axis_name
    return P(*spec)


def param_partition_specs(params, mesh_cfg: MeshConfig):
    """PartitionSpec pytree for model params under the configured strategy.

    Leaves under a top-level "blocks" key are layer-stacked [L, ...]; their
    leading dim is never sharded so scan-over-layers slices stay local and
    per-layer gathers (explicit FSDP) keep working.
    """
    if mesh_cfg.strategy in ("no_shard", "shard_grad_op") or mesh_cfg.fsdp == 1:
        return jax.tree.map(lambda _: P(), params)

    def spec_for(path, leaf):
        stacked = bool(path) and getattr(path[0], "key", None) == "blocks"
        return _leaf_spec(
            tuple(leaf.shape),
            mesh_cfg.fsdp,
            "fsdp",
            min_dim=1 if stacked else 0,
        )

    return jax.tree_util.tree_map_with_path(spec_for, params)


def opt_state_partition_specs(opt_state, params_specs, mesh_cfg: MeshConfig):
    """Optimizer-state sharding. Adam moments mirror the params tree shape;
    for full_shard they follow the param specs, for shard_grad_op they are
    sharded even though params are replicated (ZeRO-2), for no_shard
    replicated. Scalar leaves (step counts) stay replicated."""
    del params_specs  # moments share param shapes; specs derive from shapes
    if mesh_cfg.strategy == "no_shard" or mesh_cfg.fsdp == 1:
        return jax.tree.map(lambda _: P(), opt_state)

    def leaf_spec(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape:
            return P()
        stacked = any(getattr(p, "key", None) == "blocks" for p in path)
        return _leaf_spec(
            shape, mesh_cfg.fsdp, "fsdp", min_dim=1 if stacked else 0
        )

    return jax.tree_util.tree_map_with_path(leaf_spec, opt_state)


def state_shardings(state: TrainState, mesh: Mesh, mesh_cfg: MeshConfig):
    """NamedSharding pytree matching a TrainState."""
    p_specs = param_partition_specs(state.params, mesh_cfg)
    o_specs = opt_state_partition_specs(state.opt_state, p_specs, mesh_cfg)

    def to_sharding(spec):
        return NamedSharding(mesh, spec)

    return TrainState(
        params=jax.tree.map(to_sharding, p_specs),
        opt_state=jax.tree.map(to_sharding, o_specs),
        step=NamedSharding(mesh, P()),
    )


def shard_train_state(
    state: TrainState, mesh: Mesh, mesh_cfg: MeshConfig
) -> tuple[TrainState, TrainState]:
    """Place a host/replicated TrainState onto the mesh per the strategy.

    Returns (sharded_state, shardings). This is the moment FSDP 'wraps' the
    model in the reference (train_fsdp.py:64-81) — here it is just a
    device_put with sharding annotations; XLA does the rest.
    """
    shardings = state_shardings(state, mesh, mesh_cfg)
    sharded = jax.device_put(state, shardings)
    return sharded, shardings
