"""Explicit-collective (shard_map) DP/FSDP — the teaching/trace-parity path.

The pjit path (parallel/api.py) lets XLA place collectives. This module
writes them BY HAND inside ``shard_map``, so the program text (and the
profile) shows exactly the communication pattern the reference's torch
wrappers issue imperatively:

  DDP (no_shard):
    - each device computes grads on its batch shard, accumulating over
      micro-batches with NO communication — the ``model.no_sync()`` analogue
      (reference distributed_trainer.py:115-127) is simply *not psum-ing*;
    - ONE ``lax.pmean(grads, axes)`` at the accumulation boundary — the
      bucketed all-reduce of the DDP C++ reducer (reference train_ddp.py:46-49);
    - ``lax.pmean(loss)`` — the explicit all_reduce(AVG) of
      reference distributed_trainer.py:131-154.

  FSDP full_shard (ZeRO-3):
    - params live sharded along "fsdp"; each scanned layer ``all_gather``s
      its block params just-in-time (reference: per-wrapped-module gather,
      train_fsdp.py:50-52,71-81);
    - the backward of that gather IS reduce-scatter: AD transposes
      ``all_gather`` to ``psum_scatter``, so gradient reduce-scatter appears
      without being written;
    - remat of the scanned block re-gathers in backward, matching FSDP's
      free-after-use + re-gather-in-backward behavior;
    - optimizer update runs on the local shard only.

  FSDP shard_grad_op (ZeRO-2):
    - params replicated in compute (no forward gather);
    - grads ``psum_scatter``-ed along "fsdp" (+ pmean over "data");
    - sharded Adam update, then the updated shards are re-materialised with
      a psum of disjoint padded slices — numerically an all_gather, but
      typed invariant under check_vma (reference train_fsdp.py:52-53).

  FSDP shard_opt (ZeRO-1):
    - params AND grads replicated (plain all-reduce like DDP);
    - each shard slices params+grads to its fsdp slice, runs the Adam
      update against its optimizer-state shard, and the updated slices
      are re-materialised — only the optimizer memory is sharded.

  Tensor parallelism ("tensor" axis, Megatron-style):
    - block params sharded head-/column-aligned (parallel/sharding.py);
      the model runs on local heads with the tp_copy/tp_reduce conjugate
      pair (ops/tp.py) at the parallel-region boundaries — one psum after
      each row-parallel projection in forward, one per region in backward;
    - composes with every strategy above and with ring attention ("seq").

Numerical contract: identical results to the single-device step and the pjit
path (tested in tests/test_parallel.py) — psum ordering and mean-vs-sum
conventions are pinned by those tests.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.utils.compat import shard_map

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig
from pytorch_distributed_tpu.models import ModelApi
from pytorch_distributed_tpu.ops.losses import (
    cross_entropy_loss,
    linear_cross_entropy,
)
from pytorch_distributed_tpu.ops.tp import pvary_missing
from pytorch_distributed_tpu.parallel.mesh import (
    batch_partition_spec,
    fold_batch_shard_key,
)
from pytorch_distributed_tpu.parallel.sharding import param_partition_specs
from pytorch_distributed_tpu.parallel.zero import (
    clip_by_global_norm_typed,
    gather_params as _gather_params,
    scatter_grads as _scatter_grads,
    scatter_grads_bucketed as _scatter_grads_bucketed,
    spec_has as _spec_has,
    zero_sharded_update,
)
from pytorch_distributed_tpu.train.state import TrainState


def _dp_axes(mesh_cfg: MeshConfig) -> tuple[str, ...]:
    """Axes the batch is split over (grad-reduction axes)."""
    return tuple(ax for ax in ("data", "fsdp") if getattr(mesh_cfg, ax) > 1)


def make_explicit_train_step(
    model: ModelApi,
    model_cfg: ModelConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    mesh_cfg: MeshConfig,
    state: TrainState,
    *,
    grad_clip_norm: float | None = None,
    accum_dtype: str = "float32",
) -> Callable:
    """Build a jitted explicit-collective (state, batch, key) -> (state,
    metrics) step. State must already be placed per
    parallel.sharding.shard_train_state (same shardings as the pjit path).

    ``grad_clip_norm``: global-norm gradient clipping, computed from the
    psum'd global norm (shards see the SAME clip scale). The ``tx`` passed
    in must be clip-free (``make_optimizer(cfg, with_clip=False)``) —
    ``optax.clip_by_global_norm`` inside shard_map would compute a
    shard-local norm on fsdp-sharded grads, silently applying a different
    scale per shard."""
    tensor_axis = "tensor" if mesh_cfg.tensor > 1 else None
    seq_axis = "seq" if mesh_cfg.seq > 1 else None
    expert_axis = "expert" if mesh_cfg.expert > 1 else None
    if expert_axis is not None:
        if not model_cfg.n_experts:
            raise ValueError(
                "expert axis > 1 needs an MoE model (n_experts > 0)"
            )
        if model_cfg.n_experts % mesh_cfg.expert:
            raise ValueError(
                f"n_experts={model_cfg.n_experts} not divisible by "
                f"expert={mesh_cfg.expert}"
            )
        # seq composes too: context parallelism shards the TOKEN dim, and
        # routing is per-token — each seq shard routes its local tokens
        # through the same all_to_all expert exchange (capacity counted
        # per shard, like the data axis). Equivalence-tested in
        # tests/test_moe.py.
    # Dropout-rejection checks are gated on the gpt2 family: llama's
    # apply()/run_blocks ignore dropout keys entirely (dropout-free BY
    # DESIGN), so a hand-built llama ModelConfig — whose *_pdrop fields
    # default nonzero — must not be spuriously rejected for seq/tensor
    # meshes it trains identically on.
    _gpt2 = model_cfg.family == "gpt2"
    if (
        _gpt2
        and seq_axis is not None
        and model_cfg.attn_pdrop > 0
        and model_cfg.seq_impl != "ulysses"
    ):
        # Fail at build time, not mid-trace on the first step. Ulysses IS
        # supported: its local attention covers the full sequence for the
        # shard's own head group, and fold_batch_shard_key already gives
        # each seq shard an independent key (ops/ulysses.py). Ring has no
        # attention-dropout support (weights only exist per KV block
        # inside the online-softmax merge).
        raise NotImplementedError(
            "attention dropout is not supported with ring-attention "
            f"sequence parallelism (attn_pdrop={model_cfg.attn_pdrop}); "
            "set attn_pdrop=0.0 or use seq_impl='ulysses'"
        )
    if (
        _gpt2
        and tensor_axis is not None
        and model_cfg.attn_pdrop > 0
        and model_cfg.tensor_dropout != "folded"
    ):
        # Per-shard draws from the replicated key would give head groups on
        # different shards identical (correlated) masks that also differ
        # from the single-device draw — silently breaking the parity
        # contract. No modern config trains with attention dropout anyway.
        # cfg.tensor_dropout="folded" opts into per-shard folded keys
        # (statistically equivalent, not bitwise — see config.py).
        raise NotImplementedError(
            "attention dropout is not supported with explicit tensor "
            f"parallelism (attn_pdrop={model_cfg.attn_pdrop}); set "
            "attn_pdrop=0.0 or opt into tensor_dropout='folded'"
        )
    strategy = mesh_cfg.strategy
    fsdp_size = mesh_cfg.fsdp
    dp_axes = _dp_axes(mesh_cfg)
    p_specs = param_partition_specs(state.params, mesh_cfg)
    from pytorch_distributed_tpu.parallel.sharding import (
        opt_state_partition_specs,
    )

    o_specs = opt_state_partition_specs(state.opt_state, p_specs, mesh_cfg)
    # ZeRO-2 shards grads/opt-state in the layout params WOULD have under
    # full_shard, even though params stay replicated.
    shard_specs = param_partition_specs(
        state.params, dataclasses.replace(mesh_cfg, strategy="full_shard")
    )
    batch_spec = batch_partition_spec(mesh_cfg)
    train_mode = (
        model_cfg.embd_pdrop > 0
        or model_cfg.attn_pdrop > 0
        or model_cfg.resid_pdrop > 0
    )

    # Per-layer specs for stacked block leaves: drop the (never-sharded)
    # leading layer dim, since scan slices it off before the gather runs.
    if strategy == "full_shard" and fsdp_size > 1:
        block_specs = jax.tree.map(
            lambda s: P(*s[1:]),
            p_specs["blocks"],
            is_leaf=lambda x: isinstance(x, P),
        )

        def gather_block(bp):
            return _gather_params(bp, block_specs)

        # Latency-hiding schedule: prefetch the next N layers' gathers
        # ahead of the current layer's compute (ops/layer_scan.py).
        # Bit-equivalent to the just-in-time schedule — only the issue
        # order of the (deterministic) all_gathers changes.
        prefetch_buffers = mesh_cfg.prefetch_buffers
    else:
        gather_block = None
        prefetch_buffers = 0

    def forward_loss(params_shard, inputs, targets, key):
        if train_mode:
            # Independent dropout masks per batch/sequence shard — the
            # shared shard_map-path convention (parallel/mesh.py).
            key = fold_batch_shard_key(key, mesh_cfg)
        if strategy == "full_shard" and fsdp_size > 1:
            # Non-block leaves (embeddings, final norm) are gathered up
            # front; each scanned layer gathers its own block just in time
            # via block_transform, and remat re-gathers in backward.
            params = {
                k: (
                    v
                    if k == "blocks"
                    else _gather_params(v, p_specs[k])
                )
                for k, v in params_shard.items()
            }
        else:
            params = params_shard
        fused = model_cfg.fused_head_ce
        out = model.apply(
            params,
            inputs,
            model_cfg,
            deterministic=not train_mode,
            dropout_key=key,
            block_transform=gather_block,
            seq_axis=seq_axis,
            tensor_axis=tensor_axis,
            expert_axis=expert_axis,
            return_aux=bool(model_cfg.n_experts),
            return_hidden=fused,
            prefetch_buffers=prefetch_buffers,
        )
        out, aux = out if model_cfg.n_experts else (out, 0.0)
        if fused:
            # Head matmul fused into the loss: the [B, T, V] logits tensor
            # never exists (ops/losses.linear_cross_entropy). Under seq
            # sharding the hidden rows are this shard's local tokens — the
            # local-mean loss the seq pmean below averages, exactly like
            # the unfused path; under full_shard `params` is the gathered
            # tree, so the head weight is whole.
            w, layout = model.head_weight(params)
            loss = linear_cross_entropy(
                out.reshape(-1, out.shape[-1]),
                w,
                targets.reshape(-1),
                w_layout=layout,
                logits_dtype=model_cfg.logits_dtype,
            )
        else:
            loss = cross_entropy_loss(out, targets)
        if model_cfg.n_experts:
            loss = loss + model_cfg.moe_aux_coef * aux
        return loss

    grad_fn = jax.value_and_grad(forward_loss)

    # Axes along which per-shard values actually vary (sharded batch and/or
    # sharded params). Fresh constants (the scan's zero accumulators) start
    # typed as unvarying under check_vma; they must be pcast to match the
    # varying gradients/losses the scan body produces.
    vary_axes = tuple(
        ax
        for ax in ("data", "fsdp", "seq", "expert")
        if getattr(mesh_cfg, ax) > 1
    )

    def _vary(x):
        return pvary_missing(x, vary_axes)

    def _vary_like(z, ref):
        """pcast z to vary on ref's axes plus the batch axes — the vma its
        gradient will have (tensor-sharded params produce tensor-varying
        grads; replicated params produce tensor-invariant grads via the
        tp_copy backward psum)."""
        target = set(
            getattr(getattr(ref, "aval", None), "vma", frozenset())
        ) | set(vary_axes)
        return pvary_missing(z, tuple(target))

    def step_impl(state: TrainState, batch: dict, dropout_key: jax.Array):
        accum = batch["inputs"].shape[0]

        # Differentiate w.r.t. params CAST TO VARYING: if params stayed typed
        # as invariant, vma-aware AD would insert an automatic psum into the
        # transpose at every micro-batch — both defeating the no_sync
        # semantics (communication deferred to the boundary) and
        # double-counting with the explicit pmean below. With varying params
        # AD produces the per-shard local gradient and every collective in
        # this step is one written by hand.
        vparams = jax.tree.map(_vary, state.params)

        # --- local gradient accumulation: NO collectives inside ----------
        def scan_body(carry, xs):
            grads_acc, loss_acc = carry
            inputs, targets, idx = xs
            key = jax.random.fold_in(dropout_key, idx)
            loss, grads = grad_fn(vparams, inputs, targets, key)
            return (
                # Accumulate in the buffer dtype (plain + would promote
                # bf16 buffers back to f32).
                jax.tree.map(
                    lambda a, g: a + g.astype(a.dtype), grads_acc, grads
                ),
                loss_acc + loss,
            ), None

        zeros = jax.tree.map(
            lambda p: _vary_like(
                jnp.zeros(p.shape, jnp.dtype(accum_dtype)), p
            ),
            state.params,
        )
        (grads, loss_sum), _ = jax.lax.scan(
            scan_body,
            (zeros, _vary(jnp.zeros((), jnp.float32))),
            (batch["inputs"], batch["targets"], jnp.arange(accum)),
        )
        grads = jax.tree.map(lambda g: g / accum, grads)
        loss = loss_sum / accum

        # --- the boundary: collectives fire here -------------------------
        if strategy == "full_shard" and fsdp_size > 1:
            # Sharded leaves: AD transposed the all_gather into a
            # psum_scatter that SUMMED the per-shard grads over fsdp —
            # normalise into a mean. Leaves with no fsdp-divisible dim were
            # never gathered, so their grads are still per-shard partials:
            # a real pmean over fsdp.
            grads = jax.tree.map(
                lambda g, spec: (
                    g / fsdp_size
                    if _spec_has(spec, "fsdp")
                    else jax.lax.pmean(g, "fsdp")
                ),
                grads,
                p_specs,
            )
            if "data" in dp_axes and mesh_cfg.data > 1:
                grads = jax.lax.pmean(grads, "data")
        elif strategy == "shard_grad_op" and fsdp_size > 1:
            # ZeRO-2: reduce_scatter to shards (+ mean over data axis).
            # rs_buckets > 0 coalesces the per-leaf scatters into bucketed
            # collectives (parallel/zero.py) — numerically identical, and
            # the downstream sharded update consumes the same layout.
            if mesh_cfg.rs_buckets > 0:
                grads = _scatter_grads_bucketed(
                    grads, shard_specs, fsdp_size, mesh_cfg.rs_buckets
                )
            else:
                grads = _scatter_grads(grads, shard_specs, fsdp_size)
            grads = jax.tree.map(lambda g: g / fsdp_size, grads)
            if "data" in dp_axes and mesh_cfg.data > 1:
                grads = jax.lax.pmean(grads, "data")
        else:
            # DDP: one all-reduce(AVG) over every batch axis.
            for ax in dp_axes:
                grads = jax.lax.pmean(grads, ax)

        # Expert-axis reduction — orthogonal to the ZeRO level, applied
        # under every strategy: expert-sharded leaves already hold the SUM
        # over all expert-shards' tokens (the backward all_to_all routed
        # every token's contribution to its expert's owner) — normalise by
        # the shard count; everything else is a per-shard partial needing a
        # real pmean over the expert axis. (Under full_shard the fsdp
        # normalisation above already ran per-leaf; the two axes reduce
        # independently.)
        if expert_axis is not None:
            grads = jax.tree.map(
                lambda g, spec: (
                    g / mesh_cfg.expert
                    if _spec_has(spec, "expert")
                    else jax.lax.pmean(g, expert_axis)
                ),
                grads,
                p_specs,
            )

        # Context parallelism: params are replicated across "seq", each shard
        # computed grads of its local-token mean loss — the global-mean grad
        # and loss are the seq-average of both.
        if seq_axis is not None:
            grads = jax.lax.pmean(grads, seq_axis)
            loss = jax.lax.pmean(loss, seq_axis)

        # loss all-reduce(AVG) (reference distributed_trainer.py:131-154).
        for ax in dp_axes:
            loss = jax.lax.pmean(loss, ax)
        if expert_axis is not None:
            loss = jax.lax.pmean(loss, expert_axis)

        # grad_norm over the distributed grad tree: each leaf's squared sum
        # is psum'd over exactly the axes that leaf is sharded over (fsdp
        # and/or tensor); leaves replicated on an axis must NOT be summed
        # over it. Computed BEFORE the update so it can drive clipping.
        norm_specs = (
            shard_specs
            if strategy in ("full_shard", "shard_grad_op") and fsdp_size > 1
            else p_specs
        )
        spec_leaves = jax.tree.leaves(
            norm_specs, is_leaf=lambda x: isinstance(x, P)
        )
        buckets: dict = {}
        for g, spec in zip(jax.tree.leaves(grads), spec_leaves):
            axes = tuple(
                ax
                for ax in ("fsdp", "tensor", "expert")
                if getattr(mesh_cfg, ax) > 1 and _spec_has(spec, ax)
            )
            buckets[axes] = buckets.get(axes, 0.0) + jnp.sum(
                jnp.square(g.astype(jnp.float32))
            )
        sq = jnp.zeros((), jnp.float32)
        for axes, val in buckets.items():
            for ax in axes:
                val = jax.lax.psum(val, ax)
            sq = sq + val
        grad_norm = jnp.sqrt(sq)

        if grad_clip_norm is not None:
            # Shared typed global-norm clip (parallel/zero.py) — same
            # helper the pipeline path uses, so the semantics cannot
            # diverge.
            grads = clip_by_global_norm_typed(grads, grad_norm, grad_clip_norm)

        # --- update -------------------------------------------------------
        if strategy in ("shard_grad_op", "shard_opt") and fsdp_size > 1:
            # ZeRO-2 / ZeRO-1 sharded update + re-materialise
            # (parallel/zero.py — shared with the pipeline path).
            new_params, new_opt_state = zero_sharded_update(
                tx, state.params, state.opt_state, grads, shard_specs,
                fsdp_size, strategy,
            )
        else:
            updates, new_opt_state = tx.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)

        metrics = {"loss": loss, "grad_norm": grad_norm}
        return TrainState(new_params, new_opt_state, state.step + 1), metrics

    smapped = shard_map(
        step_impl,
        mesh=mesh,
        in_specs=(
            TrainState(params=p_specs, opt_state=o_specs, step=P()),
            {"inputs": batch_spec, "targets": batch_spec},
            P(),
        ),
        out_specs=(
            TrainState(params=p_specs, opt_state=o_specs, step=P()),
            {"loss": P(), "grad_norm": P()},
        ),
        # Varying-manual-axes typing ON: a future edit that breaks a
        # replication invariant (e.g. returning a per-shard value through a
        # P() out_spec) fails at trace time instead of silently producing
        # wrong numbers.
        check_vma=True,
    )
    return jax.jit(smapped, donate_argnums=(0,))

