"""Parallel train step: the pjit/NamedSharding ("automatic") path.

The single-device train step (train/trainer.py) is already a pure function;
making it DDP or FSDP is *only* a matter of sharding annotations — XLA's SPMD
partitioner inserts the same collectives torch issues imperatively:

  DDP        → gradient all-reduce (reference DDP reducer; here: psum placed
               at the accumulation boundary because grads of sharded-batch
               loss feed a replicated weight update)
  FSDP full  → all_gather(params) before use + reduce_scatter(grads)
               (reference train_fsdp.py:50-52)
  FSDP grad_op → reduce_scatter(grads) + sharded update + all_gather(params)

The loss the step returns is already the global mean over the sharded batch —
the explicit ``dist.all_reduce(loss, AVG)`` of reference
distributed_trainer.py:131-154 is subsumed by SPMD semantics.

An explicit `shard_map` twin of this path (collectives written by hand, for
teaching/trace parity) lives in parallel/explicit.py.
"""

from __future__ import annotations


import jax
from jax.sharding import Mesh, NamedSharding

import optax

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig
from pytorch_distributed_tpu.models import ModelApi
from pytorch_distributed_tpu.parallel.mesh import (
    batch_partition_spec,
    make_batch_put,
)
from pytorch_distributed_tpu.parallel.sharding import (
    param_partition_specs,
    state_shardings,
)
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.trainer import make_train_step


def make_parallel_train_step(
    model: ModelApi,
    model_cfg: ModelConfig,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    mesh_cfg: MeshConfig,
    state: TrainState,
    *,
    accum_dtype: str = "float32",
    guard=None,
):
    """Returns (train_step, batch_put) for a sharded TrainState.

    ``train_step`` has the same (state, batch, key) -> (state, metrics)
    signature as the single-device step; ``batch_put`` places a host
    [A, B_global, T] batch onto the mesh with the batch sharding (B split
    over data×fsdp axes, T over seq).
    """
    shardings = state_shardings(state, mesh, mesh_cfg)
    batch_spec = batch_partition_spec(mesh_cfg)  # P(None, batch_axes, seq)
    # Logits [B, T, V]: batch/seq sharded like the inputs, vocab replicated.
    logits_sharding = NamedSharding(
        mesh, jax.sharding.PartitionSpec(batch_spec[1], batch_spec[2], None)
    )
    # Gradients follow the ZeRO level, not the param placement: under
    # shard_grad_op params are replicated but grads reduce-scatter onto the
    # optimizer-state shards.
    grad_shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_partition_specs(state.params, mesh_cfg, for_grads=True),
    )
    base_step = make_train_step(
        model,
        model_cfg,
        tx,
        jit=False,
        logits_sharding=logits_sharding,
        grad_shardings=grad_shardings,
        accum_dtype=accum_dtype,
        guard=guard,
    )
    batch_sharding = NamedSharding(mesh, batch_spec)
    metrics_sharding = NamedSharding(mesh, jax.sharding.PartitionSpec())

    metrics_shardings = {"loss": metrics_sharding, "grad_norm": metrics_sharding}
    if guard is not None:
        metrics_shardings["anomaly"] = metrics_sharding
    step = jax.jit(
        base_step,
        in_shardings=(
            shardings,
            {"inputs": batch_sharding, "targets": batch_sharding},
            None,
        ),
        out_shardings=(shardings, metrics_shardings),
        donate_argnums=(0,),
    )

    return step, make_batch_put(mesh, mesh_cfg)
