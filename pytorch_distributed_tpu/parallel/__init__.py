from pytorch_distributed_tpu.parallel.mesh import (  # noqa: F401
    batch_partition_spec,
    make_mesh,
    process_info,
)
from pytorch_distributed_tpu.parallel.sharding import (  # noqa: F401
    param_partition_specs,
    shard_train_state,
)
from pytorch_distributed_tpu.parallel.api import (  # noqa: F401
    make_parallel_train_step,
)
