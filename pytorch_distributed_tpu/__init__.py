"""pytorch_distributed_tpu — a TPU-native distributed-training framework.

A brand-new JAX/XLA framework providing the full capability surface of the
reference teaching repo ``yash-malik/pytorch-distributed`` (see SURVEY.md):

- self-contained GPT-2 (merged QKV, pre-norm, tied head, GPT-2 init) with
  selective activation checkpointing — as pure functions over a params pytree;
- kjj0 fineweb10B ``.bin`` data pipeline with deterministic rank-sliced loading;
- a jitted training loop with gradient accumulation, checkpoint/resume and
  process-0 logging;
- data-parallel (DDP-equivalent) and fully-sharded (ZeRO-2/3-equivalent)
  training expressed as sharding over a `jax.sharding.Mesh` with XLA
  collectives (psum / all_gather / psum_scatter) instead of NCCL;
- measurement tooling: analytic + measured memory accounting, fenced
  throughput benchmarking, scheduled profiler traces, and trace analysis.

Layout:
  models/    GPT-2 and Llama-style model families (pure init/apply functions)
  ops/       attention variants (naive, flash/Pallas, ring), remat policies
  parallel/  mesh helpers, DP/FSDP sharding strategies, collective wrappers
  data/      .bin shard format, sequential + distributed loaders, synthetic data
  train/     train state, optimizer, Trainer/DistributedTrainer, checkpointing
  profiling/ profiler schedule/traces, memory accounting, throughput harness,
             trace analysis (temporal breakdown, comm/comp overlap, op diff)
  utils/     config-free helpers: PRNG plumbing, logging, pytree utilities
"""

__version__ = "0.1.0"

from pytorch_distributed_tpu.config import (  # noqa: F401
    DataConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
