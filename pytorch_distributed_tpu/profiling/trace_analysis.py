"""Trace analysis over Chrome-trace JSON (the HTA analogue).

The reference's analysis notebook (reference analyze_traces.ipynb) runs
Holistic Trace Analysis over Kineto Chrome traces. Our profiler emits
Chrome-trace JSON too (``*.trace.json.gz`` from jax.profiler with device-side
"XLA Ops"/"Async XLA Ops" tracks), so this module reimplements the three
analyses the notebook uses, framework-natively:

- ``temporal_breakdown``   — compute / communication / memcpy / idle time on
                             the device (HTA get_temporal_breakdown);
- ``comm_comp_overlap``    — how much communication is hidden under compute
                             (HTA get_comm_comp_overlap: exposed vs hidden);
- ``ops_diff``             — per-op count/duration diff between two traces,
                             e.g. baseline vs DDP shows the added collectives
                             (HTA TraceDiff.compare_traces + ops_diff, incl.
                             the notebook's collective-name filter).

Pure stdlib (json/gzip); works on any Trace Event Format file.
"""

from __future__ import annotations

import gzip
import json
from collections import defaultdict
from pathlib import Path

_COMM_MARKERS = (
    # Hyphen-normalised (classify_op folds "_" -> "-"): catches XLA's
    # all-gather / all_gather / allgather spellings plus async -start/-done
    # forms, on both HLO instruction names and profiler trace rows. Pinned
    # against the compiler's actual emitted names by
    # tests/test_hlo_collectives.py.
    "all-reduce", "allreduce", "all-gather", "allgather", "reduce-scatter",
    "reducescatter", "collective-permute", "all-to-all", "alltoall",
    "ragged-all-to-all", "psum", "pmean", "ppermute", "send", "recv",
    "collective",
)
_MEMCPY_MARKERS = ("copy-start", "copy-done", "copy.", "memcpy", "transpose-copy")
_INFRA_MARKERS = ("infeed", "outfeed", "host-callback")


def classify_op(name: str) -> str:
    n = name.lower().replace("_", "-")
    if any(m in n for m in _COMM_MARKERS):
        return "communication"
    if any(m in n for m in _MEMCPY_MARKERS):
        return "memcpy"
    if any(m in n for m in _INFRA_MARKERS):
        return "infra"
    return "compute"


def load_trace(path: str | Path) -> dict:
    path = Path(path)
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        return json.load(f)


def _device_pids(trace: dict) -> set[int]:
    pids = set()
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = (e.get("args") or {}).get("name", "")
            if "TPU" in name or "GPU" in name or "device" in name.lower():
                if "CPU" not in name and "host" not in name.lower():
                    pids.add(e["pid"])
    return pids


def _op_threads(trace: dict, pids: set[int]) -> set[tuple[int, int]]:
    """(pid, tid) pairs for per-op device tracks ('XLA Ops' and async)."""
    keys = set()
    for e in trace.get("traceEvents", []):
        if (
            e.get("ph") == "M"
            and e.get("name") == "thread_name"
            and e.get("pid") in pids
        ):
            tname = (e.get("args") or {}).get("name", "")
            if "XLA Ops" in tname or "Async" in tname or "Stream" in tname:
                keys.add((e["pid"], e["tid"]))
    return keys


# XLA:CPU runtime threads that execute HLO thunks (the virtual-device rig,
# --xla_force_host_platform_device_count): per-op events carry the SAME HLO
# instruction names the TPU path emits (all_gather.N, reduce_scatter.N,
# fusion.N, ...), so classify_op's HLO-name pinning
# (tests/test_hlo_collectives.py) applies unchanged.
_CPU_RUNTIME_THREADS = (
    "tf_XLAEigen",
    "tf_XLAPjRtCpuClient",
    # Older PJRT CPU runtime (jax 0.4.x) names its thunk threadpool after
    # the TFRT client instead.
    "tf_XLATfrtCpuClient",
)
# Runtime bookkeeping rows interleaved with the op rows on those threads:
# "end: <op>" cleanup markers (would double-count the op name) and the
# thunk-executor / threadpool / transpose-plan internals that NEST around
# real ops.
_CPU_INFRA_PREFIXES = (
    "end: ", "ThunkExecutor", "ThreadpoolListener", "Transpose",
    "TfrtCpuExecutable",
)


def _cpu_runtime_threads(trace: dict) -> set[tuple[int, int]]:
    keys = set()
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tname = (e.get("args") or {}).get("name", "")
            if tname.startswith(_CPU_RUNTIME_THREADS):
                keys.add((e["pid"], e["tid"]))
    return keys


def device_op_events(trace: dict) -> list[dict]:
    """Complete ('X') events on device per-op tracks:
    [{name, ts, dur, pid, tid, category}].

    Falls back to the XLA:CPU runtime threads when the trace has no
    TPU/GPU device tracks (a virtual-device CPU capture): the CPU backend
    runs HLO thunks on host threadpool threads, and its per-op rows — real
    collectives included — are the same analysis surface."""
    pids = _device_pids(trace)
    threads = _op_threads(trace, pids)
    cpu_fallback = not threads
    if cpu_fallback:
        threads = _cpu_runtime_threads(trace)
    out = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in threads:
            continue
        if cpu_fallback and e["name"].startswith(_CPU_INFRA_PREFIXES):
            continue
        dur = float(e.get("dur", 0.0))
        out.append(
            {
                "name": e["name"],
                "ts": float(e.get("ts", 0.0)),
                "dur": dur,
                "pid": e["pid"],
                "tid": e["tid"],
                "category": classify_op(e["name"]),
            }
        )
    return out


def _merge_intervals(intervals: list[tuple[float, float]]):
    if not intervals:
        return []
    intervals = sorted(intervals)
    merged = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [(s, e) for s, e in merged]


def _total(intervals) -> float:
    return sum(e - s for s, e in intervals)


def _intersect(a, b):
    """Intersection of two merged interval lists."""
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            out.append((s, e))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def temporal_breakdown(trace: dict) -> dict:
    """Device time split into compute / communication / memcpy / idle over
    the span of device activity (HTA get_temporal_breakdown analogue).
    Overlapped comm+compute time counts as compute (busy), matching the
    'non-compute = exposed only' convention."""
    events = device_op_events(trace)
    if not events:
        return {
            "total_us": 0.0, "busy_us": 0.0, "idle_us": 0.0,
            "compute_us": 0.0, "communication_us": 0.0,
            "communication_exposed_us": 0.0, "memcpy_us": 0.0,
            "idle_pct": 0.0, "compute_pct": 0.0, "communication_pct": 0.0,
            "communication_exposed_pct": 0.0, "memcpy_pct": 0.0,
        }
    by_cat = defaultdict(list)
    for ev in events:
        by_cat[ev["category"]].append((ev["ts"], ev["ts"] + ev["dur"]))
    merged = {c: _merge_intervals(iv) for c, iv in by_cat.items()}

    all_iv = _merge_intervals(
        [iv for ivs in merged.values() for iv in ivs]
    )
    t0 = min(s for s, _ in all_iv)
    t1 = max(e for _, e in all_iv)
    total = t1 - t0
    busy = _total(all_iv)

    compute = _total(merged.get("compute", []))
    comm_iv = merged.get("communication", [])
    comm_exposed = _total(comm_iv) - _total(
        _intersect(comm_iv, merged.get("compute", []))
    )
    memcpy = _total(merged.get("memcpy", []))

    def pct(x):
        return 100.0 * x / total if total else 0.0

    return {
        "total_us": total,
        "busy_us": busy,
        "idle_us": total - busy,
        "compute_us": compute,
        "communication_us": _total(comm_iv),
        "communication_exposed_us": comm_exposed,
        "memcpy_us": memcpy,
        "compute_pct": pct(compute),
        "communication_pct": pct(_total(comm_iv)),
        "communication_exposed_pct": pct(comm_exposed),
        "memcpy_pct": pct(memcpy),
        "idle_pct": pct(total - busy),
    }


def comm_comp_overlap(trace: dict) -> dict:
    """Communication overlapped-with-compute vs exposed
    (HTA get_comm_comp_overlap: overlap% = hidden comm / total comm)."""
    events = device_op_events(trace)
    comp = _merge_intervals(
        [
            (e["ts"], e["ts"] + e["dur"])
            for e in events
            if e["category"] == "compute"
        ]
    )
    comm = _merge_intervals(
        [
            (e["ts"], e["ts"] + e["dur"])
            for e in events
            if e["category"] == "communication"
        ]
    )
    total_comm = _total(comm)
    hidden = _total(_intersect(comm, comp))
    return {
        "comm_total_us": total_comm,
        "comm_hidden_us": hidden,
        "comm_exposed_us": total_comm - hidden,
        "overlap_pct": 100.0 * hidden / total_comm if total_comm else 0.0,
        "exposed_pct": (
            100.0 * (total_comm - hidden) / total_comm if total_comm else 0.0
        ),
    }


def op_summary(trace: dict) -> dict[str, dict]:
    """Per-op-name totals: {name: {count, total_us, mean_us, category}}."""
    out: dict[str, dict] = {}
    for e in device_op_events(trace):
        rec = out.setdefault(
            e["name"],
            {"count": 0, "total_us": 0.0, "category": e["category"]},
        )
        rec["count"] += 1
        rec["total_us"] += e["dur"]
    for rec in out.values():
        rec["mean_us"] = rec["total_us"] / rec["count"]
    return out


def ops_diff(
    trace_a: dict, trace_b: dict, *, only_categories=None, top: int = 0
) -> dict:
    """Operator diff between two traces (TraceDiff analogue): ops added in b,
    removed from b, and shared ops with count/duration deltas. Use
    ``only_categories={'communication'}`` for the notebook's collective
    filter (nccl/allreduce/allgather/reduce_scatter/broadcast)."""
    a, b = op_summary(trace_a), op_summary(trace_b)

    def keep(name, rec):
        return only_categories is None or rec["category"] in only_categories

    added = {
        n: r for n, r in b.items() if n not in a and keep(n, r)
    }
    removed = {
        n: r for n, r in a.items() if n not in b and keep(n, r)
    }
    changed = {}
    for n in set(a) & set(b):
        if not keep(n, b[n]):
            continue
        changed[n] = {
            "count_a": a[n]["count"],
            "count_b": b[n]["count"],
            "total_us_a": a[n]["total_us"],
            "total_us_b": b[n]["total_us"],
            "delta_us": b[n]["total_us"] - a[n]["total_us"],
            "category": b[n]["category"],
        }
    if top:
        def trim(d, key):
            return dict(
                sorted(d.items(), key=key, reverse=True)[:top]
            )

        added = trim(added, lambda kv: kv[1]["total_us"])
        removed = trim(removed, lambda kv: kv[1]["total_us"])
        changed = trim(changed, lambda kv: abs(kv[1]["delta_us"]))
    return {"added": added, "removed": removed, "changed": changed}
