"""Throughput measurement, batch sweeps, and scaling extrapolation.

Capability twin of reference assignment0/throughput.py:
- tokens/sec + steps/sec over a fenced timing window after warmup
  (reference :13-83: dummy random data, 5 warmup, 20 timed,
  cuda.synchronize-fenced). TPU-native fencing: device_get of a step output
  — on this environment ``block_until_ready`` is not a reliable fence and
  deterministic re-runs can be served from a relay cache, so data is
  freshly seeded per call (see bench.py);
- throughput vs batch-size sweep with OOM catch + peak memory per point
  (reference :132-181);
- "modern training" extrapolation to huge params/tokens under a linear
  FLOPs-scaling assumption (reference :86-129).
"""

from __future__ import annotations

import os
import time

import numpy as np

from pytorch_distributed_tpu.config import ModelConfig, TrainConfig


def _fresh_seed() -> int:
    return int.from_bytes(os.urandom(4), "little")


def measure_tokens_per_second(
    cfg: ModelConfig,
    *,
    batch_size: int = 8,
    seq_len: int = 1024,
    num_steps: int = 20,
    warmup_steps: int = 5,
    seed: int | None = None,
) -> dict:
    """Train-step throughput on dummy data (reference :13-83 defaults:
    B=8, T=1024, 5 warmup + 20 timed)."""
    import jax

    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.train.trainer import make_train_step
    from pytorch_distributed_tpu.utils.prng import domain_key

    seed = _fresh_seed() if seed is None else seed
    model = get_model(cfg)
    tcfg = TrainConfig(
        global_batch_size=batch_size,
        micro_batch_size=batch_size,
        num_steps=num_steps,
        learning_rate=3e-4,
    )
    tx = make_optimizer(tcfg)
    params = model.init(domain_key(seed, "init"), cfg)
    n_params = int(
        sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    )
    state = init_train_state(params, tx)
    step = make_train_step(model, cfg, tx)

    rng = np.random.default_rng(seed)
    batch = {
        "inputs": jax.numpy.asarray(
            rng.integers(0, cfg.vocab_size, (1, batch_size, seq_len)),
            dtype=jax.numpy.int32,
        ),
        "targets": jax.numpy.asarray(
            rng.integers(0, cfg.vocab_size, (1, batch_size, seq_len)),
            dtype=jax.numpy.int32,
        ),
    }
    dkey = domain_key(seed, "dropout")

    for i in range(warmup_steps):
        state, metrics = step(state, batch, jax.random.fold_in(dkey, i))
        float(jax.device_get(metrics["loss"]))  # fence

    t0 = time.perf_counter()
    for i in range(num_steps):
        state, metrics = step(
            state, batch, jax.random.fold_in(dkey, warmup_steps + i)
        )
    float(jax.device_get(metrics["loss"]))  # fence
    elapsed = time.perf_counter() - t0

    tokens_per_batch = batch_size * seq_len  # reference TODO :41-42
    total_tokens = num_steps * tokens_per_batch
    return {
        "tokens_per_second": total_tokens / elapsed,
        "steps_per_second": num_steps / elapsed,
        "seconds_per_step": elapsed / num_steps,
        "elapsed_seconds": elapsed,
        "num_steps": num_steps,
        "batch_size": batch_size,
        "seq_len": seq_len,
        "param_count": n_params,
    }


def extrapolate_modern_training(
    measured: dict,
    *,
    target_params: float = 1e12,
    target_tokens: float = 10e12,
) -> dict:
    """Scale measured throughput to a hypothetical giant run under the
    linear-FLOPs assumption (time/token scales with param count —
    reference :86-129's 1T-param / 10T-token estimate)."""
    tps = measured["tokens_per_second"]
    n = measured["param_count"]
    scale = target_params / n
    scaled_tps = tps / scale
    seconds = target_tokens / scaled_tps
    return {
        "measured_params": n,
        "measured_tokens_per_second": tps,
        "target_params": target_params,
        "target_tokens": target_tokens,
        "scaled_tokens_per_second": scaled_tps,
        "seconds": seconds,
        "days": seconds / 86400,
        "years": seconds / (86400 * 365),
        "assumption": "linear FLOPs scaling, identical hardware+efficiency",
    }


def compare_batch_sizes(
    cfg: ModelConfig,
    *,
    batch_sizes=(1, 4, 8, 16, 32, 64),
    seq_len: int = 1024,
    num_steps: int = 10,
    warmup_steps: int = 2,
) -> list[dict]:
    """Throughput + peak memory per batch size, OOM-tolerant
    (reference :132-181: fresh model per point, catch OOM, record peak)."""
    import jax

    from pytorch_distributed_tpu.profiling.memory import measured_memory

    results = []
    for b in batch_sizes:
        try:
            r = measure_tokens_per_second(
                cfg,
                batch_size=b,
                seq_len=seq_len,
                num_steps=num_steps,
                warmup_steps=warmup_steps,
            )
            r["peak_bytes_in_use"] = measured_memory()["peak_bytes_in_use"]
            r["oom"] = False
        except jax.errors.JaxRuntimeError as e:  # RESOURCE_EXHAUSTED
            if "RESOURCE_EXHAUSTED" not in str(e) and "out of memory" not in str(e).lower():
                raise
            r = {
                "batch_size": b,
                "seq_len": seq_len,
                "oom": True,
                "error": str(e).splitlines()[0][:200],
            }
        results.append(r)
    return results
