"""Scheduled profiler tracing.

Capability twin of the reference's torch.profiler setup
(reference train_baseline.py:79-87): a step-counting schedule
(wait=2, warmup=2, active=6, repeat=1), per-rank trace outputs
(reference train_ddp.py:131-139 writes rank{r}_trace.json; here each process
writes its own trace dir), and per-step annotations
(reference train/trainer.py:111-113 steps the profiler; our Trainer calls
``profiler.step()`` once per optimizer step and wraps the step in
``profiler.step_context(n)``).

TPU-native: ``jax.profiler.start_trace/stop_trace`` produce XPlane protos
plus a Chrome-trace JSON (``*.trace.json.gz``) with device-side "XLA Ops" /
"Async XLA Ops" tracks — consumed by profiling/trace_analysis.py (the HTA
analogue). There is no CUPTI warmup on TPU, so "warmup" steps simply extend
the wait window; the active window covers the same step indices as the
reference's schedule (steps wait+warmup .. wait+warmup+active-1).
"""

from __future__ import annotations

import contextlib
import glob
import os
from pathlib import Path

import jax


class ScheduledProfiler:
    def __init__(
        self,
        trace_dir: str | Path,
        *,
        wait: int = 2,
        warmup: int = 2,
        active: int = 6,
        repeat: int = 1,
        create_perfetto_trace: bool = True,
    ):
        if active <= 0:
            raise ValueError("active must be positive")
        self.trace_dir = str(
            Path(trace_dir) / f"rank{jax.process_index()}"
        )
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self.repeat = repeat  # 0 = cycle forever, like torch.profiler
        self._perfetto = create_perfetto_trace
        self._count = 0
        self._cycles_done = 0
        self._tracing = False

    # -- schedule ---------------------------------------------------------
    def _phase(self) -> str:
        cycle_len = self.wait + self.warmup + self.active
        if self.repeat and self._cycles_done >= self.repeat:
            return "done"
        pos = self._count % cycle_len
        if pos < self.wait + self.warmup:
            return "wait"
        return "active"

    def step(self) -> None:
        """Advance the schedule by one (optimizer) step. Must be called
        exactly once per step, after the step runs (reference trainer.py
        calls profiler.step() at the end of each micro-batch; see
        train/trainer.py for why ours counts optimizer steps)."""
        self._count += 1
        phase = self._phase()
        if phase == "active" and not self._tracing:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(
                self.trace_dir,
                create_perfetto_trace=self._perfetto,
            )
            self._tracing = True
        elif phase != "active" and self._tracing:
            self._stop()
            self._cycles_done += 1

    def _stop(self) -> None:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False

    def step_context(self, step_num: int):
        """Context manager annotating one train step in the trace."""
        if self._tracing or self._phase() == "active":
            return jax.profiler.StepTraceAnnotation(
                "train_step", step_num=step_num
            )
        return contextlib.nullcontext()

    def close(self) -> None:
        self._stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def find_trace_files(trace_dir: str | Path, pattern: str = "*.trace.json.gz"):
    """Locate Chrome-trace JSONs under a (possibly per-rank) trace dir."""
    return sorted(
        glob.glob(str(Path(trace_dir) / "**" / pattern), recursive=True)
    )
