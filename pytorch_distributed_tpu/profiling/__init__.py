from pytorch_distributed_tpu.profiling.profiler import (  # noqa: F401
    ScheduledProfiler,
    find_trace_files,
)
from pytorch_distributed_tpu.profiling.memory import (  # noqa: F401
    analytic_memory_breakdown,
    measured_memory,
    save_memory_snapshot,
)
from pytorch_distributed_tpu.profiling.throughput import (  # noqa: F401
    compare_batch_sizes,
    extrapolate_modern_training,
    measure_tokens_per_second,
)
