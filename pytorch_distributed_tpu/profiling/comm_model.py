"""Analytic communication-overhead model for multi-chip projections.

The benchmark rig has ONE real chip (BENCH methodology), so any multi-chip
number in RESULTS.md is a *projection*, not a measurement. This module makes
that projection explicit and auditable: given a parameter count, a mesh
size, and the measured single-chip step time, it computes the per-step
collective traffic each parallelism strategy implies (the same accounting
the reference's FSDP docs describe: per-block all_gather in forward,
re-gather + reduce_scatter in backward, reference train_fsdp.py:49-59) and
turns it into a projected step-time / MFU *band*.

Why a band, not a number: two genuinely uncertain factors —

- effective per-chip ICI bandwidth a collective sustains (link count,
  bidirectional rings, protocol efficiency), bracketed by
  ``ici_eff_low/high``;
- compute/communication overlap achieved by XLA's latency-hiding scheduler,
  bracketed by no-overlap (t_comp + t_comm) and full-overlap
  (max(t_comp, t_comm)).

Chip constants are public-spec numbers, recorded here as assumptions, not
measurements (v5e: 197 TFLOP/s bf16 peak; 1,600 Gbps aggregate ICI per
chip over 4 links in a 2D torus -> ~50-100 GB/s per-chip effective
collective throughput; the band below is deliberately conservative).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_bf16_flops: float
    # Effective per-chip ICI bytes/s a ring collective sustains, bracketed.
    ici_eff_low: float
    ici_eff_high: float
    hbm_bytes: float


V5E = ChipSpec(
    name="v5e",
    peak_bf16_flops=197e12,
    ici_eff_low=45e9,
    ici_eff_high=90e9,
    hbm_bytes=16e9,
)


def fsdp_comm_bytes_per_step(
    n_params: int,
    n_chips: int,
    *,
    param_bytes: int = 2,
    grad_bytes: int | None = None,
) -> dict:
    """Per-chip collective traffic of one ZeRO-3 (full_shard) step.

    Ring-collective accounting (each of the three collectives moves the
    full tensor minus this chip's shard through each chip's links):

    - forward:  all_gather(params)            -> P * (N-1)/N bytes
    - backward: re-gather under remat         -> P * (N-1)/N bytes
    - backward: reduce_scatter(grads)         -> G * (N-1)/N bytes
    """
    if n_chips < 2:
        return {"all_gather": 0.0, "reduce_scatter": 0.0, "total": 0.0}
    grad_bytes = param_bytes if grad_bytes is None else grad_bytes
    frac = (n_chips - 1) / n_chips
    ag = 2.0 * n_params * param_bytes * frac
    rs = float(n_params) * grad_bytes * frac
    return {"all_gather": ag, "reduce_scatter": rs, "total": ag + rs}


def ddp_comm_bytes_per_step(
    n_params: int, n_chips: int, *, grad_bytes: int = 4
) -> dict:
    """Per-chip traffic of one DDP step: one ring all-reduce of the grads
    (= reduce_scatter + all_gather, 2 * G * (N-1)/N bytes)."""
    if n_chips < 2:
        return {"all_reduce": 0.0, "total": 0.0}
    frac = (n_chips - 1) / n_chips
    ar = 2.0 * n_params * grad_bytes * frac
    return {"all_reduce": ar, "total": ar}


def zero_memory_per_chip(
    n_params: int,
    n_chips: int,
    *,
    strategy: str = "full_shard",
    param_bytes: int = 2,
    grad_bytes: int | None = None,
    opt_bytes: int | None = None,
) -> dict:
    """Per-chip STATE memory (params + grads + Adam moments) under each
    ZeRO level — the analytic feasibility check for configs the rig
    cannot run (e.g. BASELINE config 5, llama3-8B on v5e-64). Activation
    memory is workload-dependent and excluded; treat the result as the
    floor a chip must clear before batch size enters the picture.

    opt_bytes: bytes per param for BOTH Adam moments together (default
    2 * param_bytes)."""
    grad_bytes = param_bytes if grad_bytes is None else grad_bytes
    opt_bytes = 2 * param_bytes if opt_bytes is None else opt_bytes
    n = max(1, n_chips)
    full = {
        "params": float(n_params * param_bytes),
        "grads": float(n_params * grad_bytes),
        "opt": float(n_params * opt_bytes),
    }
    sharded_keys = {
        "full_shard": ("params", "grads", "opt"),  # ZeRO-3
        "shard_grad_op": ("grads", "opt"),  # ZeRO-2
        "shard_opt": ("opt",),  # ZeRO-1
        "no_shard": (),  # DDP
    }
    if strategy not in sharded_keys:
        raise ValueError(f"unknown strategy {strategy!r}")
    out = {
        k: (v / n if k in sharded_keys[strategy] else v)
        for k, v in full.items()
    }
    out["total"] = sum(out.values())
    return out


def project_step(
    *,
    comm_bytes: float,
    compute_ms: float,
    chip: ChipSpec = V5E,
) -> dict:
    """Projected per-step time band [best, worst] in ms.

    best  = full overlap at the optimistic bandwidth: max(comp, comm_fast)
    worst = zero overlap at the conservative bandwidth: comp + comm_slow
    """
    comm_fast_ms = comm_bytes / chip.ici_eff_high * 1e3
    comm_slow_ms = comm_bytes / chip.ici_eff_low * 1e3
    return {
        "comm_ms_band": (comm_fast_ms, comm_slow_ms),
        "step_ms_band": (
            max(compute_ms, comm_fast_ms),
            compute_ms + comm_slow_ms,
        ),
    }


def project_fsdp_mfu(
    *,
    n_params: int,
    n_chips: int,
    measured_ms_per_step: float,
    measured_mfu_pct: float,
    param_bytes: int = 2,
    grad_bytes: int | None = None,
    chip: ChipSpec = V5E,
) -> dict:
    """Project a measured single-chip (no-communication) step onto an
    N-chip FSDP mesh with the SAME per-chip batch (weak scaling: per-chip
    compute time unchanged, collective traffic added on top).

    Returns the projected MFU band: measured_mfu * compute / step_time for
    the [best, worst] step-time band — the honest version of a "fsdp8 MFU"
    table entry (VERDICT r2 weak #1).
    """
    traffic = fsdp_comm_bytes_per_step(
        n_params, n_chips, param_bytes=param_bytes, grad_bytes=grad_bytes
    )
    proj = project_step(
        comm_bytes=traffic["total"], compute_ms=measured_ms_per_step,
        chip=chip,
    )
    best_ms, worst_ms = proj["step_ms_band"]
    return {
        "chip": chip.name,
        "n_chips": n_chips,
        "comm_bytes_per_step": traffic,
        "comm_ms_band": proj["comm_ms_band"],
        "step_ms_band": (best_ms, worst_ms),
        "mfu_pct_band": (
            measured_mfu_pct * measured_ms_per_step / worst_ms,
            measured_mfu_pct * measured_ms_per_step / best_ms,
        ),
        "assumptions": (
            f"{chip.name} public specs; ici_eff "
            f"{chip.ici_eff_low/1e9:.0f}-{chip.ici_eff_high/1e9:.0f} GB/s "
            "per chip; overlap bracketed none..full; weak scaling (same "
            "per-chip batch); single-chip measured compute time"
        ),
    }
