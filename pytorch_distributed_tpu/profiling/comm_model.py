"""Analytic communication-overhead model for multi-chip projections.

The benchmark rig has ONE real chip (BENCH methodology), so any multi-chip
number in RESULTS.md is a *projection*, not a measurement. This module makes
that projection explicit and auditable: given a parameter count, a mesh
size, and the measured single-chip step time, it computes the per-step
collective traffic each parallelism strategy implies (the same accounting
the reference's FSDP docs describe: per-block all_gather in forward,
re-gather + reduce_scatter in backward, reference train_fsdp.py:49-59) and
turns it into a projected step-time / MFU *band*.

Why a band, not a number: two genuinely uncertain factors —

- effective per-chip ICI bandwidth a collective sustains (link count,
  bidirectional rings, protocol efficiency), bracketed by
  ``ici_eff_low/high``;
- compute/communication overlap achieved by XLA's latency-hiding scheduler,
  bracketed by no-overlap (t_comp + t_comm) and full-overlap
  (max(t_comp, t_comm)).

Chip constants are public-spec numbers, recorded here as assumptions, not
measurements (v5e: 197 TFLOP/s bf16 peak; 1,600 Gbps aggregate ICI per
chip over 4 links in a 2D torus -> ~50-100 GB/s per-chip effective
collective throughput; the band below is deliberately conservative).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_bf16_flops: float
    # Effective per-chip ICI bytes/s a ring collective sustains, bracketed.
    ici_eff_low: float
    ici_eff_high: float
    hbm_bytes: float


V5E = ChipSpec(
    name="v5e",
    peak_bf16_flops=197e12,
    ici_eff_low=45e9,
    ici_eff_high=90e9,
    hbm_bytes=16e9,
)


def fsdp_comm_bytes_per_step(
    n_params: int,
    n_chips: int,
    *,
    param_bytes: int = 2,
    grad_bytes: int | None = None,
) -> dict:
    """Per-chip collective traffic of one ZeRO-3 (full_shard) step.

    Ring-collective accounting (each of the three collectives moves the
    full tensor minus this chip's shard through each chip's links):

    - forward:  all_gather(params)            -> P * (N-1)/N bytes
    - backward: re-gather under remat         -> P * (N-1)/N bytes
    - backward: reduce_scatter(grads)         -> G * (N-1)/N bytes
    """
    if n_chips < 2:
        return {"all_gather": 0.0, "reduce_scatter": 0.0, "total": 0.0}
    grad_bytes = param_bytes if grad_bytes is None else grad_bytes
    frac = (n_chips - 1) / n_chips
    ag = 2.0 * n_params * param_bytes * frac
    rs = float(n_params) * grad_bytes * frac
    return {"all_gather": ag, "reduce_scatter": rs, "total": ag + rs}


def ddp_comm_bytes_per_step(
    n_params: int, n_chips: int, *, grad_bytes: int = 4
) -> dict:
    """Per-chip traffic of one DDP step: one ring all-reduce of the grads
    (= reduce_scatter + all_gather, 2 * G * (N-1)/N bytes)."""
    if n_chips < 2:
        return {"all_reduce": 0.0, "total": 0.0}
    frac = (n_chips - 1) / n_chips
    ar = 2.0 * n_params * grad_bytes * frac
    return {"all_reduce": ar, "total": ar}


def zero1_comm_bytes_per_step(
    n_params: int,
    n_chips: int,
    *,
    param_bytes: int = 4,
    grad_bytes: int = 4,
) -> dict:
    """Per-chip traffic of one ZeRO-1 (shard_opt) step, mirroring
    parallel/explicit.py's structure: grads replicated-all-reduced like
    DDP (2 * G * (N-1)/N), then the sharded optimizer's updated param
    shards re-materialise via a psum of disjoint padded slices —
    numerically an all_gather, emitted as an all-reduce
    (2 * P * (N-1)/N)."""
    if n_chips < 2:
        return {"grad_all_reduce": 0.0, "param_all_reduce": 0.0,
                "total": 0.0}
    frac = (n_chips - 1) / n_chips
    g_ar = 2.0 * n_params * grad_bytes * frac
    p_ar = 2.0 * n_params * param_bytes * frac
    return {
        "grad_all_reduce": g_ar,
        "param_all_reduce": p_ar,
        "total": g_ar + p_ar,
    }


def zero2_comm_bytes_per_step(
    n_params: int,
    n_chips: int,
    *,
    param_bytes: int = 4,
    grad_bytes: int = 4,
) -> dict:
    """Per-chip traffic of one ZeRO-2 (shard_grad_op) step, mirroring
    parallel/explicit.py: grads reduce-scattered onto the optimizer
    shards (G * (N-1)/N) and the updated params re-materialised via the
    same disjoint-slice psum as ZeRO-1 (an all-reduce,
    2 * P * (N-1)/N). Bucketing the reduce-scatter (rs_buckets) changes
    the instruction count, never these bytes."""
    if n_chips < 2:
        return {"reduce_scatter": 0.0, "param_all_reduce": 0.0,
                "total": 0.0}
    frac = (n_chips - 1) / n_chips
    rs = float(n_params) * grad_bytes * frac
    p_ar = 2.0 * n_params * param_bytes * frac
    return {
        "reduce_scatter": rs,
        "param_all_reduce": p_ar,
        "total": rs + p_ar,
    }


def zero_memory_per_chip(
    n_params: int,
    n_chips: int,
    *,
    strategy: str = "full_shard",
    param_bytes: int = 2,
    grad_bytes: int | None = None,
    opt_bytes: int | None = None,
) -> dict:
    """Per-chip STATE memory (params + grads + Adam moments) under each
    ZeRO level — the analytic feasibility check for configs the rig
    cannot run (e.g. BASELINE config 5, llama3-8B on v5e-64). Activation
    memory is workload-dependent and excluded; treat the result as the
    floor a chip must clear before batch size enters the picture.

    opt_bytes: bytes per param for BOTH Adam moments together (default
    2 * param_bytes)."""
    grad_bytes = param_bytes if grad_bytes is None else grad_bytes
    opt_bytes = 2 * param_bytes if opt_bytes is None else opt_bytes
    n = max(1, n_chips)
    full = {
        "params": float(n_params * param_bytes),
        "grads": float(n_params * grad_bytes),
        "opt": float(n_params * opt_bytes),
    }
    sharded_keys = {
        "full_shard": ("params", "grads", "opt"),  # ZeRO-3
        "shard_grad_op": ("grads", "opt"),  # ZeRO-2
        "shard_opt": ("opt",),  # ZeRO-1
        "no_shard": (),  # DDP
    }
    if strategy not in sharded_keys:
        raise ValueError(f"unknown strategy {strategy!r}")
    out = {
        k: (v / n if k in sharded_keys[strategy] else v)
        for k, v in full.items()
    }
    out["total"] = sum(out.values())
    return out


def project_step(
    *,
    comm_bytes: float,
    compute_ms: float,
    chip: ChipSpec = V5E,
) -> dict:
    """Projected per-step time band [best, worst] in ms.

    best  = full overlap at the optimistic bandwidth: max(comp, comm_fast)
    worst = zero overlap at the conservative bandwidth: comp + comm_slow
    """
    comm_fast_ms = comm_bytes / chip.ici_eff_high * 1e3
    comm_slow_ms = comm_bytes / chip.ici_eff_low * 1e3
    return {
        "comm_ms_band": (comm_fast_ms, comm_slow_ms),
        "step_ms_band": (
            max(compute_ms, comm_fast_ms),
            compute_ms + comm_slow_ms,
        ),
    }


def project_step_overlap(
    *,
    comm_bytes: float,
    compute_ms: float,
    overlap_fraction: float,
    chip: ChipSpec = V5E,
) -> dict:
    """Overlap-aware step projection: split collective time into HIDDEN
    (under compute) vs EXPOSED (serialising the step) at a given achieved
    overlap fraction, instead of bracketing none..full like
    ``project_step``.

    ``overlap_fraction`` is the fraction of total collective time hidden
    under compute — the same quantity ``trace_analysis.comm_comp_overlap``
    measures (overlap_pct / 100), so a measured trace number plugs in
    directly. Hidden time is additionally capped by the compute time
    itself: no schedule can hide more communication than there is compute
    to hide it under. step = compute + exposed, per ici band.
    """
    if not 0.0 <= overlap_fraction <= 1.0:
        raise ValueError(
            f"overlap_fraction must be in [0, 1], got {overlap_fraction}"
        )

    def split(comm_ms: float) -> tuple[float, float]:
        hidden = min(comm_ms * overlap_fraction, compute_ms)
        return hidden, comm_ms - hidden

    comm_fast_ms = comm_bytes / chip.ici_eff_high * 1e3
    comm_slow_ms = comm_bytes / chip.ici_eff_low * 1e3
    hid_fast, exp_fast = split(comm_fast_ms)
    hid_slow, exp_slow = split(comm_slow_ms)
    return {
        "overlap_fraction": overlap_fraction,
        "comm_ms_band": (comm_fast_ms, comm_slow_ms),
        "hidden_ms_band": (hid_fast, hid_slow),
        "exposed_ms_band": (exp_fast, exp_slow),
        "step_ms_band": (
            compute_ms + exp_fast,
            compute_ms + exp_slow,
        ),
    }


def project_fsdp_prefetch_mfu(
    *,
    n_params: int,
    n_layer: int,
    n_chips: int,
    measured_ms_per_step: float,
    measured_mfu_pct: float,
    prefetch_buffers: int = 1,
    param_bytes: int = 2,
    grad_bytes: int | None = None,
    chip: ChipSpec = V5E,
) -> dict:
    """``project_fsdp_mfu`` for the double-buffered prefetch schedule
    (parallel/explicit.py prefetch_buffers): instead of bracketing
    overlap none..full, model what the schedule can actually hide.

    Pipeline accounting, assuming per-layer uniform traffic/compute:

    - startup: the first window's W = prefetch_buffers + 1 layer gathers
      run before any compute exists to hide them — always exposed;
    - drain: the first layer's backward reduce-scatter completes after
      the last backward compute — always exposed;
    - steady state: the remaining traffic hides under compute up to the
      compute time itself (comm-bound meshes still expose the excess).

    exposed = startup + drain + max(0, comm_rest - compute). The band
    comes from the ici bracket, like every projection here."""
    traffic = fsdp_comm_bytes_per_step(
        n_params, n_chips, param_bytes=param_bytes, grad_bytes=grad_bytes
    )
    window = min(max(1, prefetch_buffers + 1), max(1, n_layer))

    def project(ici_bytes_per_s: float) -> tuple[float, float]:
        comm_ms = traffic["total"] / ici_bytes_per_s * 1e3
        ag_layer_ms = (
            traffic["all_gather"] / ici_bytes_per_s * 1e3 / (2 * n_layer)
        )
        rs_layer_ms = (
            traffic["reduce_scatter"] / ici_bytes_per_s * 1e3 / n_layer
        )
        startup = window * ag_layer_ms
        drain = rs_layer_ms
        rest = max(0.0, comm_ms - startup - drain)
        exposed = startup + drain + max(
            0.0, rest - measured_ms_per_step
        )
        return exposed, comm_ms

    exp_fast, comm_fast = project(chip.ici_eff_high)
    exp_slow, comm_slow = project(chip.ici_eff_low)
    best_ms = measured_ms_per_step + exp_fast
    worst_ms = measured_ms_per_step + exp_slow
    return {
        "chip": chip.name,
        "n_chips": n_chips,
        "prefetch_buffers": prefetch_buffers,
        "comm_bytes_per_step": traffic,
        "comm_ms_band": (comm_fast, comm_slow),
        "exposed_ms_band": (exp_fast, exp_slow),
        "hidden_ms_band": (comm_fast - exp_fast, comm_slow - exp_slow),
        "step_ms_band": (best_ms, worst_ms),
        "mfu_pct_band": (
            measured_mfu_pct * measured_ms_per_step / worst_ms,
            measured_mfu_pct * measured_ms_per_step / best_ms,
        ),
        "assumptions": (
            f"{chip.name} public specs; ici_eff "
            f"{chip.ici_eff_low/1e9:.0f}-{chip.ici_eff_high/1e9:.0f} GB/s "
            "per chip; uniform per-layer traffic; prefetch hides steady-"
            "state gathers/scatters under compute, exposing only the "
            f"{window}-layer startup gather + 1-layer drain scatter "
            "(+ any comm-bound excess); weak scaling"
        ),
    }


def project_fsdp_mfu(
    *,
    n_params: int,
    n_chips: int,
    measured_ms_per_step: float,
    measured_mfu_pct: float,
    param_bytes: int = 2,
    grad_bytes: int | None = None,
    chip: ChipSpec = V5E,
) -> dict:
    """Project a measured single-chip (no-communication) step onto an
    N-chip FSDP mesh with the SAME per-chip batch (weak scaling: per-chip
    compute time unchanged, collective traffic added on top).

    Returns the projected MFU band: measured_mfu * compute / step_time for
    the [best, worst] step-time band — the honest version of a "fsdp8 MFU"
    table entry (VERDICT r2 weak #1).
    """
    traffic = fsdp_comm_bytes_per_step(
        n_params, n_chips, param_bytes=param_bytes, grad_bytes=grad_bytes
    )
    proj = project_step(
        comm_bytes=traffic["total"], compute_ms=measured_ms_per_step,
        chip=chip,
    )
    best_ms, worst_ms = proj["step_ms_band"]
    return {
        "chip": chip.name,
        "n_chips": n_chips,
        "comm_bytes_per_step": traffic,
        "comm_ms_band": proj["comm_ms_band"],
        "step_ms_band": (best_ms, worst_ms),
        "mfu_pct_band": (
            measured_mfu_pct * measured_ms_per_step / worst_ms,
            measured_mfu_pct * measured_ms_per_step / best_ms,
        ),
        "assumptions": (
            f"{chip.name} public specs; ici_eff "
            f"{chip.ici_eff_low/1e9:.0f}-{chip.ici_eff_high/1e9:.0f} GB/s "
            "per chip; overlap bracketed none..full; weak scaling (same "
            "per-chip batch); single-chip measured compute time"
        ),
    }


def ring_attention_comm_bytes_per_step(
    *,
    n_layer: int,
    batch: int,
    t_local: int,
    kv_dim: int,
    n_chips: int,
    dtype_bytes: int = 2,
    ring_passes: float = 3.0,
) -> dict:
    """Per-chip ppermute traffic of ring (context-parallel) attention
    (ops/ring_attention.py): each ring pass streams every OTHER chip's K
    and V blocks through this chip — (n_chips - 1) hops x 2 tensors x
    [batch, t_local, kv_dim] bytes — once per layer.

    ring_passes: 1 forward + ~2 backward (the rematted recompute ring plus
    the dK/dV accumulation ring) = 3 by default; an assumption, bracketed
    by the ici band like everything else in this module.
    """
    if n_chips < 2:
        return {"ppermute": 0.0, "total": 0.0}
    per_layer = (n_chips - 1) * 2.0 * batch * t_local * kv_dim * dtype_bytes
    total = ring_passes * n_layer * per_layer
    return {"ppermute": total, "total": total}


def project_ring_mfu(
    *,
    measured_ms_per_step: float,
    n_params: int,
    n_layer: int,
    n_embd: int,
    kv_dim: int,
    batch: int,
    t_local: int,
    n_chips: int,
    dtype_bytes: int = 2,
    ring_passes: float = 3.0,
    chip: ChipSpec = V5E,
) -> dict:
    """Project a measured single-chip long-context step (T = t_local) onto
    an n_chips ring-attention mesh holding T_global = n_chips * t_local.

    Sequence (weak) scaling: each chip keeps its B x t_local token shard,
    so per-token attention FLOPs grow with the GLOBAL context — per-chip
    compute time scales by fpt(T_global) / fpt(T_local) at constant
    compute efficiency — and the ring's KV ppermute traffic lands on top
    (overlap bracketed none..full, like project_fsdp_mfu).
    """
    t_global = n_chips * t_local
    fpt_local = 6.0 * n_params + 12.0 * n_layer * n_embd * t_local
    fpt_global = 6.0 * n_params + 12.0 * n_layer * n_embd * t_global
    compute_ms = measured_ms_per_step * fpt_global / fpt_local
    traffic = ring_attention_comm_bytes_per_step(
        n_layer=n_layer, batch=batch, t_local=t_local, kv_dim=kv_dim,
        n_chips=n_chips, dtype_bytes=dtype_bytes, ring_passes=ring_passes,
    )
    proj = project_step(
        comm_bytes=traffic["total"], compute_ms=compute_ms, chip=chip
    )
    best_ms, worst_ms = proj["step_ms_band"]
    tokps_band = (
        batch * t_local / worst_ms * 1e3,
        batch * t_local / best_ms * 1e3,
    )
    return {
        "chip": chip.name,
        "n_chips": n_chips,
        "t_global": t_global,
        "comm_bytes_per_step": traffic,
        "comm_ms_band": proj["comm_ms_band"],
        "compute_ms": compute_ms,
        "step_ms_band": (best_ms, worst_ms),
        "tokps_per_chip_band": tokps_band,
        "mfu_pct_band": tuple(
            t * fpt_global / chip.peak_bf16_flops * 100 for t in tokps_band
        ),
        "assumptions": (
            f"{chip.name} public specs; ici_eff "
            f"{chip.ici_eff_low/1e9:.0f}-{chip.ici_eff_high/1e9:.0f} GB/s; "
            f"overlap bracketed none..full; sequence weak scaling (same "
            f"B x T_local per chip, attention FLOPs at T_global); "
            f"{ring_passes:.0f} ring passes/layer (fwd + remat recompute + "
            "dK/dV)"
        ),
    }
