"""Memory accounting: analytic breakdown + measured device stats + snapshot.

Capability twin of reference assignment0/memory_analysis.py:
- analytic fp32 breakdown params/grads/Adam-moments (P*4 + P*4 + 2*P*4 bytes,
  reference :12-52), extended with an activation estimate that understands
  our remat modes;
- empirical measurement (reference :105-110 memory_allocated/reserved) via
  ``device.memory_stats()`` (TPU: bytes_in_use / peak_bytes_in_use);
- allocation snapshot for offline viewing (reference :112-117 dumps a pickle
  for pytorch.org/memory_viz) via
  ``jax.profiler.save_device_memory_profile`` (pprof format).
"""

from __future__ import annotations

from pathlib import Path

import jax
import numpy as np

from pytorch_distributed_tpu.config import ModelConfig


def _model_param_count(cfg: ModelConfig) -> int:
    from pytorch_distributed_tpu.models import get_model

    shapes = jax.eval_shape(
        lambda k: get_model(cfg).init(k, cfg), jax.random.key(0)
    )
    return int(
        sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))
    )


def activation_bytes_estimate(
    cfg: ModelConfig, batch_size: int, seq_len: int
) -> int:
    """Rough per-step live-activation bytes under our remat policy.

    With per-block remat saving dot outputs ("dots"), the dominant saved
    tensors per layer are the block I/O plus matmul outputs
    (qkv 3E, attn-out E, c_fc F, c_proj E per token); without remat, add the
    attention score matrices (H*T^2) and softmax outputs.
    """
    act_itemsize = 2 if cfg.dtype == "bfloat16" else 4
    b, t, e, f, h, l = (
        batch_size, seq_len, cfg.n_embd, cfg.inner_dim, cfg.n_head,
        cfg.n_layer,
    )
    per_layer_tokens = b * t * (e + 3 * e + e + f + e)  # x, qkv, attn, fc, proj
    if cfg.remat == "none":
        per_layer_tokens += b * t * (2 * e)  # ln outputs
        score_bytes = l * b * h * t * t * 4 * 2  # scores+softmax in f32
    elif cfg.remat == "full":
        per_layer_tokens = b * t * e  # only block inputs saved
        score_bytes = 0
    elif cfg.remat == "flash":
        # Only the flash kernel's (o, l, m) per layer — the long-context
        # policy; the o save is E per token, l/m are f32 [B, H, T].
        per_layer_tokens = b * t * (e + e)  # block input + o
        score_bytes = l * b * h * t * 4 * 2  # l and m, f32
    else:  # dots / dots_no_batch / names
        score_bytes = 0
    logits_bytes = (
        0 if cfg.fused_head_ce else b * t * cfg.vocab_size * 4
    )
    return l * per_layer_tokens * act_itemsize + score_bytes + logits_bytes


def analytic_memory_breakdown(
    cfg: ModelConfig,
    *,
    batch_size: int = 8,
    seq_len: int = 1024,
    optimizer: str = "adamw",
) -> dict:
    """Estimated training-memory breakdown in bytes
    (reference memory_analysis.py:12-52, defaults :136-138: gpt2-small,
    B=8, T=1024)."""
    n = _model_param_count(cfg)
    param_itemsize = 2 if cfg.param_dtype == "bfloat16" else 4
    params_b = n * param_itemsize
    grads_b = n * 4  # grads accumulate in f32
    opt_mult = {"adamw": 2, "adam": 2, "sgd": 0, "momentum": 1}[optimizer]
    opt_b = opt_mult * n * 4
    act_b = activation_bytes_estimate(cfg, batch_size, seq_len)
    total = params_b + grads_b + opt_b + act_b
    return {
        "param_count": n,
        "params_bytes": params_b,
        "grads_bytes": grads_b,
        "optimizer_bytes": opt_b,
        "activations_bytes_estimate": act_b,
        "total_bytes_estimate": total,
        "total_gib_estimate": total / 2**30,
        "config": {
            "batch_size": batch_size,
            "seq_len": seq_len,
            "remat": cfg.remat,
            "dtype": cfg.dtype,
            "param_dtype": cfg.param_dtype,
        },
    }


def measured_memory(device=None) -> dict:
    """Live/peak device memory (reference :105-110's
    memory_allocated/memory_reserved analogue). Returns zeros when the
    backend exposes no stats (CPU)."""
    device = device or jax.local_devices()[0]
    stats = device.memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
        "raw": dict(stats),
    }


def snapshot_supported(device=None) -> bool:
    """Whether the backend can produce a device-memory profile. Relay/proxy
    PJRT backends that expose no memory stats also lack the executable
    heap-profile C API — calling it there aborts the PROCESS (absl fatal in
    PJRT_Executable_SizeOfGeneratedCodeInBytes), so callers must gate on
    this instead of try/except."""
    device = device or jax.local_devices()[0]
    return bool(device.memory_stats() or device.platform == "cpu")


def save_memory_snapshot(path: str | Path) -> str | None:
    """Dump the current device-memory profile (pprof .prof — open with
    ``pprof`` or pprof-web; the memory_viz-pickle analogue of
    reference :112-117). Returns None (no file) when the backend cannot
    produce one — see snapshot_supported."""
    if not snapshot_supported():
        return None
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    jax.profiler.save_device_memory_profile(str(path))
    return str(path)


def compiled_memory_analysis(fn, *example_args) -> dict | None:
    """Exact compile-time HBM accounting from XLA's buffer assignment.

    Lowers + compiles ``fn`` on the example arguments and returns the
    compiler's memory numbers — the same figures an HBM OOM error reports
    ("Program hbm requirement ..."), available BEFORE running anything.
    Unlike ``measured_memory`` this works on backends with no runtime
    memory stats (the relay TPU), and is the idiomatic TPU answer to the
    reference's allocator-history accounting (SURVEY.md §2.3: HLO
    buffer-assignment dump). Returns None if the backend or jax version
    does not expose the analysis.
    """
    try:
        # Already-jitted callables lower directly (preserving donation /
        # aliasing); plain functions get wrapped.
        # repolint: allow(jit-donation-decision) — wraps the USER's fn
        # purely to lower it; adding donation would skew the
        # alias/argument byte accounting this function reports.
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jitted.lower(*example_args).compile()
        ma = compiled.memory_analysis()
    except (AttributeError, NotImplementedError, jax.errors.JaxRuntimeError):
        return None
    if ma is None:
        return None
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "code_bytes": int(ma.generated_code_size_in_bytes),
        "peak_bytes": int(getattr(ma, "peak_memory_in_bytes", 0)),
        # What must fit in HBM simultaneously: live args (minus donated
        # aliases) + outputs + program temporaries.
        "total_bytes": int(
            ma.argument_size_in_bytes
            - ma.alias_size_in_bytes
            + ma.output_size_in_bytes
            + ma.temp_size_in_bytes
        ),
    }


def compare_estimate_vs_measured(
    cfg: ModelConfig, *, batch_size: int = 8, seq_len: int = 1024
) -> dict:
    """Side-by-side analytic estimate vs measured peak
    (reference :152-163)."""
    est = analytic_memory_breakdown(
        cfg, batch_size=batch_size, seq_len=seq_len
    )
    meas = measured_memory()
    est_total = est["total_bytes_estimate"]
    peak = meas["peak_bytes_in_use"]
    return {
        "estimated": est,
        "measured": meas,
        "ratio_measured_over_estimated": (peak / est_total) if est_total else None,
    }
