"""Static peak-HBM estimation over optimized HLO text.

The serving/perf claims since PR 8 are *bytes* claims — the paged pool is
smaller than the dense cache, int8 pages are ~0.28x their f32 twins,
donation keeps the KV cache single-buffered — but none of that was
statically contractual: a regression that breaks an input/output alias or
doubles a live buffer only surfaces as a runtime OOM on hardware the CPU
rig does not have. This module prices the compiled artifact instead: it
parses the post-scheduling HLO module text (the same ``compiled.as_text()``
the collective/donation checks already consume) and derives a peak
live-bytes estimate per computation from buffer sizes + a liveness linear
scan.

Model (and its honest limits):

- **Buffer sizes** come from each instruction's declared result shape
  (``f32[4,16]{1,0}`` -> 256 bytes, tuples sum their components,
  sub-byte dtypes round up to whole bytes).
- **Liveness** is a linear scan over the instruction order of each
  computation. The module header carries ``is_scheduled=true``: the text
  order IS the execution order (the same property
  ``hlo.async_collective_pairs`` relies on), so "defined at i, last used
  at j" brackets the interval the buffer occupies memory. Peak = the
  maximum over program points of the live-interval byte sum.
- **Aliasing**: ``get-tuple-element``/``bitcast`` results are views, a
  ``tuple`` is a table over its operands, and a ``while`` loops in place
  over its carry buffer — none of them allocate; their uses count as uses
  of the underlying buffer(s).
- **Donation** (``input_output_alias`` in the module header) is honored
  as bytes actually saved: an output component that XLA aliased to a
  donated parameter writes INTO the parameter's buffer, so the output's
  own allocation is credited away and the parameter stays live to the
  end. ``alias_saved_bytes`` reports exactly how many peak bytes donation
  bought — the number that silently becomes 0 when a shape change makes
  XLA reject the alias.
- **Scoping**: every named computation (while bodies/conds, fusion
  bodies, reduce applicators, conditional branches) gets its own
  estimate, so a decode loop's steady-state footprint is separable from
  the prefill around it. In the parent scan, a ``while``/``conditional``
  instruction contributes its body's *internal* temporaries (body peak
  minus the carry the parent already counts) at its program point;
  fusion internals never materialize and contribute only the fusion's
  result buffer.

What this is NOT: the runtime allocator. XLA's buffer assignment packs
temp buffers into reused slabs, pads for layout, and on TPU tiles to
(8, 128) lanes — measured ``peak_bytes_in_use`` on hardware can sit above
(padding, fragmentation) or below (slab reuse across disjoint intervals
this scan keeps separate) the static estimate. The estimate is a
*monotone proxy*: a regression that doubles a live buffer or un-aliases a
donated input moves it loudly in the right direction, which is what the
pinned ceilings in ``budget.STABLE_MEMORY_BUDGETS`` enforce. For the
allocator's own numbers, see ``profiling/memory.compiled_memory_analysis``
(XLA buffer assignment) — the cross-check, not the contract.
"""

from __future__ import annotations

import dataclasses
import math
import re

from pytorch_distributed_tpu.analysis.hlo import parse_input_output_aliases

# Bit widths per HLO primitive type. pred is stored as a byte; sub-byte
# int4/uint4 pack two to a byte (rounded up per buffer); token/opaque
# occupy no HBM.
_DTYPE_BITS = {
    "pred": 8,
    "s2": 2, "u2": 2, "s4": 4, "u4": 4,
    "s8": 8, "u8": 8,
    "s16": 16, "u16": 16,
    "s32": 32, "u32": 32,
    "s64": 64, "u64": 64,
    "f16": 16, "bf16": 16,
    "f32": 32, "f64": 64,
    "c64": 64, "c128": 128,
    "f8e4m3": 8, "f8e4m3fn": 8, "f8e4m3b11fnuz": 8, "f8e4m3fnuz": 8,
    "f8e5m2": 8, "f8e5m2fnuz": 8, "f8e3m4": 8, "f8e8m0fnu": 8,
    "f4e2m1fn": 4,
    "token": 0, "opaque": 0,
}

# `f32[4,16]{1,0:T(8,128)}` — dims then an optional layout block (TPU
# layouts carry tiling after a colon; braces do not nest).
_ARRAY_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\](?:\{[^}]*\})?")

_INSTR_LINE_RE = re.compile(r"^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*")

# `%name (args) -> type {` / `ENTRY %name (args) -> type {`
_COMP_HEAD_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\(.*\{\s*$")

# Computation references in instruction attributes: the attr name tells
# the callee's role (used to classify computations and to decide whose
# internal temporaries surface into the parent scan). Single-name attrs
# (`body=%region_0.19`) and the brace-list form
# (`branch_computations={%a, %b}`) are separate patterns so one attr's
# capture cannot swallow the next attr's name.
_CALLED_COMP_RE = re.compile(
    r"(calls|to_apply|condition|body|true_computation|"
    r"false_computation)=%?([\w.\-]+)"
)
_BRANCHES_RE = re.compile(r"(branch_computations)=\{([^}]*)\}")

# Results of these opcodes are views over (some of) their operands, not
# fresh allocations.
_VIEW_OPCODES = frozenset({"get-tuple-element", "bitcast"})


def shape_bytes(shape: str) -> int:
    """Byte size of one HLO shape string (array or tuple).

    ``f32[4,16]{1,0}`` -> 256; ``(s32[], f32[8]{0})`` -> 36; scalars are
    rank-0 arrays (``f32[]`` -> 4); sub-byte element types round the
    whole buffer up to bytes.
    """
    shape = shape.strip()
    if shape.startswith("("):
        return sum(
            shape_bytes(part) for part in _split_tuple(shape)
        )
    m = _ARRAY_SHAPE_RE.match(shape)
    if not m:
        return 0
    dtype, dims = m.group(1), m.group(2)
    bits = _DTYPE_BITS.get(dtype)
    if bits is None:
        return 0
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return math.ceil(n * bits / 8)


def shape_dims(shape: str) -> tuple[int, ...] | None:
    """Dimension sizes of one ARRAY shape string (``f32[4,16]{1,0}`` ->
    (4, 16); scalars -> ()); None for tuples/unparseable shapes."""
    shape = shape.strip()
    if shape.startswith("("):
        return None
    m = _ARRAY_SHAPE_RE.match(shape)
    if not m:
        return None
    return tuple(
        int(d) for d in m.group(2).split(",") if d.strip()
    )


def shape_elements(shape: str) -> int:
    """Element count of one HLO shape string (tuples sum their
    components; token/opaque count zero)."""
    shape = shape.strip()
    if shape.startswith("("):
        return sum(shape_elements(part) for part in _split_tuple(shape))
    m = _ARRAY_SHAPE_RE.match(shape)
    if not m:
        return 0
    if _DTYPE_BITS.get(m.group(1), 0) == 0:
        return 0
    n = 1
    for d in m.group(2).split(","):
        if d.strip():
            n *= int(d)
    return n


def _split_tuple(shape: str) -> list[str]:
    """Top-level components of ``(a, b, (c, d))`` (paren-aware)."""
    body = shape.strip()[1:-1]
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(body):
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(body[start:i])
            start = i + 1
    if body[start:].strip():
        parts.append(body[start:])
    return parts


def _scan_shape(text: str) -> tuple[str, int] | None:
    """(shape string, end offset) at the start of ``text``: a balanced
    paren scan for tuple types, the array regex otherwise."""
    if text.startswith("("):
        depth = 0
        for i, ch in enumerate(text):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return text[: i + 1], i + 1
        return None
    m = _ARRAY_SHAPE_RE.match(text)
    if m:
        return m.group(0), m.end()
    return None


_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_OPCODE_RE = re.compile(r"\s*([\w\-]+)")


def _called_attr_pairs(text: str):
    """(role attr, callee name) pairs referenced in ``text``."""
    for cm in _CALLED_COMP_RE.finditer(text):
        yield cm.group(1), cm.group(2)
    for cm in _BRANCHES_RE.finditer(text):
        for n in re.split(r"[,\s]+", cm.group(2)):
            n = n.strip("% ")
            if n:
                yield cm.group(1), n


def _called_computations(text: str) -> list[str]:
    return [name for _, name in _called_attr_pairs(text)]


@dataclasses.dataclass(frozen=True)
class HloInstruction:
    """One parsed instruction line of a computation body."""

    name: str
    shape: str
    bytes: int
    opcode: str
    operands: tuple[str, ...]
    called: tuple[str, ...]  # computations referenced via attrs
    is_root: bool
    param_number: int | None  # for opcode == "parameter"
    # Inline operand type strings, positionally aligned with ``operands``
    # ("" where the dump omitted the type) — the cost model reads
    # contraction/operand sizes straight off the line without an
    # instruction-table lookup.
    operand_shapes: tuple[str, ...] = ()
    # Raw attribute text after the operand list (contracting dims,
    # replica_groups, backend_config with known_trip_count, ...).
    attrs: str = ""


@dataclasses.dataclass(frozen=True)
class HloComputation:
    name: str
    is_entry: bool
    instructions: tuple[HloInstruction, ...]

    @property
    def root(self) -> HloInstruction:
        for instr in self.instructions:
            if instr.is_root:
                return instr
        return self.instructions[-1]


@dataclasses.dataclass(frozen=True)
class HloModule:
    header: str
    computations: dict[str, HloComputation]
    entry: HloComputation
    # computation name -> role attr it was referenced through
    # ("body", "condition", "calls", "to_apply", ...)
    roles: dict[str, str]


_BLOCK_COMMENT_RE = re.compile(r"/\*.*?\*/")


def _parse_operand_list(body: str) -> tuple[tuple[str, ...], tuple[str, ...]]:
    """(names, inline type strings) of an operand body, split at top-level
    commas. Dumps interleave types with %-names (``dot(f32[32,64]{1,0}
    %a, ...)``) and may inject ``/*index=N*/`` comments; an operand whose
    type the dump omitted gets an empty shape string."""
    body = _BLOCK_COMMENT_RE.sub("", body)
    names: list[str] = []
    shapes: list[str] = []
    depth, start = 0, 0
    parts: list[str] = []
    for i, ch in enumerate(body):
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(body[start:i])
            start = i + 1
    if body[start:].strip():
        parts.append(body[start:])
    for part in parts:
        part = part.strip()
        nm = _OPERAND_NAME_RE.search(part)
        if not nm:
            continue
        names.append(nm.group(1))
        scanned = _scan_shape(part)
        shapes.append(scanned[0] if scanned else "")
    return tuple(names), tuple(shapes)


def _parse_instruction(line: str) -> HloInstruction | None:
    m = _INSTR_LINE_RE.match(line)
    if not m:
        return None
    is_root, name = bool(m.group(1)), m.group(2)
    rest = line[m.end():]
    scanned = _scan_shape(rest)
    if scanned is None:
        return None
    shape, off = scanned
    rest = rest[off:]
    om = _OPCODE_RE.match(rest)
    if not om:
        return None
    opcode = om.group(1)
    rest = rest[om.end():]
    # Operand body: balanced parens right after the opcode. Attrs follow.
    operands: tuple[str, ...] = ()
    operand_shapes: tuple[str, ...] = ()
    param_number = None
    attrs = rest
    if rest.startswith("("):
        depth, end = 0, len(rest)
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i + 1
                    break
        body, attrs = rest[1:end - 1], rest[end:]
        operands, operand_shapes = _parse_operand_list(body)
        if opcode == "parameter":
            try:
                param_number = int(body.strip())
            except ValueError:
                param_number = None
    called = tuple(_called_computations(attrs))
    return HloInstruction(
        name=name, shape=shape, bytes=shape_bytes(shape), opcode=opcode,
        operands=operands, called=called, is_root=is_root,
        param_number=param_number, operand_shapes=operand_shapes,
        attrs=attrs,
    )


def parse_module(hlo_text: str) -> HloModule:
    """Split compiled-module text into its computations.

    Raises ``ValueError`` when no ENTRY computation is found — an audit
    that silently estimated nothing would be worse than one that fails.
    """
    lines = hlo_text.splitlines()
    header = lines[0] if lines else ""
    computations: dict[str, HloComputation] = {}
    entry: HloComputation | None = None
    current: tuple[str, bool, list[HloInstruction]] | None = None
    for line in lines[1:]:
        stripped = line.strip()
        if current is None:
            cm = _COMP_HEAD_RE.match(stripped)
            if cm and "=" not in stripped.split("(", 1)[0]:
                current = (cm.group(2), bool(cm.group(1)), [])
            continue
        if stripped == "}":
            name, is_entry, instrs = current
            comp = HloComputation(
                name=name, is_entry=is_entry, instructions=tuple(instrs)
            )
            computations[name] = comp
            if is_entry:
                entry = comp
            current = None
            continue
        instr = _parse_instruction(line)
        if instr is not None:
            current[2].append(instr)
    if entry is None:
        raise ValueError("no ENTRY computation in HLO module text")
    # Roles come from the attr names (the instruction only kept the
    # callee names); a second cheap pass over the text keeps
    # HloInstruction flat.
    roles: dict[str, str] = {}
    for line in lines:
        for role, n in _called_attr_pairs(line):
            roles.setdefault(n, role)
    return HloModule(
        header=header, computations=computations, entry=entry, roles=roles
    )


_ROLE_KIND = {
    "body": "while-body",
    "condition": "while-cond",
    "calls": "fusion",
    "to_apply": "reduce",
    "true_computation": "branch",
    "false_computation": "branch",
    "branch_computations": "branch",
}


@dataclasses.dataclass(frozen=True)
class ComputationEstimate:
    """Liveness-scan result for one computation."""

    name: str
    kind: str  # "entry" | "while-body" | "while-cond" | "fusion" | ...
    peak_live_bytes: int
    parameter_bytes: int
    output_bytes: int
    n_instructions: int


@dataclasses.dataclass(frozen=True)
class HloParameter:
    name: str
    shape: str
    bytes: int


@dataclasses.dataclass(frozen=True)
class MemoryEstimate:
    """Static peak-HBM estimate for one compiled module."""

    entry: ComputationEstimate  # alias-credited
    raw_peak_bytes: int  # entry peak with NO alias credit
    alias_saved_bytes: int  # raw_peak_bytes - entry.peak_live_bytes
    parameters: dict[int, HloParameter]  # entry params by number
    aliased_params: frozenset[int]  # params with an accepted output alias
    computations: dict[str, ComputationEstimate]  # every non-entry comp

    @property
    def peak_live_bytes(self) -> int:
        return self.entry.peak_live_bytes

    @property
    def parameter_bytes(self) -> int:
        return sum(p.bytes for p in self.parameters.values())

    def param_bytes(self, numbers) -> int:
        """Total bytes of the named entry parameters (e.g. a donated
        argument's contiguous leaf run)."""
        return sum(
            self.parameters[n].bytes for n in numbers
            if n in self.parameters
        )

    def loop_bodies(self) -> dict[str, ComputationEstimate]:
        """The while-body computations: the decode loop's steady-state
        scope, separable from the prefill/entry around it."""
        return {
            n: c for n, c in self.computations.items()
            if c.kind == "while-body"
        }


def _underlying(comp: HloComputation) -> dict[str, frozenset]:
    """name -> the set of allocating buffers the value aliases.

    get-tuple-element/bitcast view their first operand; a tuple keeps all
    its operands reachable; a while iterates in place over its carry
    operand. Everything else (including parameters) is its own buffer.
    """
    under: dict[str, frozenset] = {}

    def resolve(name: str) -> frozenset:
        return under.get(name, frozenset({name}))

    for instr in comp.instructions:
        if instr.opcode in _VIEW_OPCODES and instr.operands:
            under[instr.name] = resolve(instr.operands[0])
        elif instr.opcode in ("tuple", "while") and instr.operands:
            merged: frozenset = frozenset()
            for op in instr.operands:
                merged |= resolve(op)
            under[instr.name] = merged
        else:
            under[instr.name] = frozenset({instr.name})
    return under


def _estimate_computation(
    comp: HloComputation,
    *,
    kind: str,
    alias_entries=(),
    extra_at: dict[int, int] | None = None,
) -> ComputationEstimate:
    """Linear-scan liveness over one computation's instruction order.

    ``alias_entries``: accepted input_output_alias entries (entry
    computation only) — each one credits the aliased output component's
    buffer away (it writes into the donated parameter's buffer) and pins
    the parameter live to the end.
    ``extra_at``: instruction index -> extra transient bytes live at that
    point (a while/conditional's internal body temporaries).
    """
    under = _underlying(comp)
    instrs = comp.instructions
    index = {instr.name: i for i, instr in enumerate(instrs)}
    sizes = {
        instr.name: instr.bytes
        for instr in instrs
        if under.get(instr.name) == frozenset({instr.name})
    }
    param_bytes = sum(
        i.bytes for i in instrs if i.opcode == "parameter"
    )

    # Donation credit: the output component's buffer writes in place into
    # the donated parameter, so it stops being its own allocation.
    params_by_number = {
        i.param_number: i.name
        for i in instrs
        if i.opcode == "parameter" and i.param_number is not None
    }
    root = comp.root
    pinned_to_end: set[str] = set(under.get(root.name, {root.name}))
    for entry_alias in alias_entries:
        if entry_alias.param_index:
            continue  # nested donated leaves: no credit (conservative)
        pname = params_by_number.get(entry_alias.param_number)
        if pname is None:
            continue
        out_name = root.name
        if root.opcode == "tuple" and len(entry_alias.output_index) == 1:
            oi = entry_alias.output_index[0]
            if oi < len(root.operands):
                out_name = root.operands[oi]
        elif entry_alias.output_index:
            continue  # deeper nesting: no credit (conservative)
        bufs = under.get(out_name, frozenset({out_name}))
        if len(bufs) != 1:
            continue
        (buf,) = bufs
        if buf != pname and buf in sizes:
            sizes[buf] = 0
            pinned_to_end.add(pname)

    n = len(instrs)
    last_use: dict[str, int] = {}
    for i, instr in enumerate(instrs):
        for op in instr.operands:
            for buf in under.get(op, frozenset({op})):
                last_use[buf] = i
    for buf in pinned_to_end:
        last_use[buf] = n
    # Parameters are materialized before the first instruction runs.
    delta = [0] * (n + 2)
    for instr in instrs:
        buf = instr.name
        if under.get(buf) != frozenset({buf}):
            continue
        size = sizes.get(buf, 0)
        if size == 0:
            continue
        start = 0 if instr.opcode == "parameter" else index[buf]
        end = last_use.get(buf, index[buf])
        delta[start] += size
        delta[end + 1] -= size
    peak, live = 0, 0
    for i in range(n + 1):
        live += delta[i]
        here = live + (extra_at or {}).get(i, 0)
        if here > peak:
            peak = here
    return ComputationEstimate(
        name=comp.name,
        kind=kind,
        peak_live_bytes=peak,
        parameter_bytes=param_bytes,
        output_bytes=root.bytes,
        n_instructions=n,
    )


def estimate_memory(hlo_text: str) -> MemoryEstimate:
    """Static peak-HBM estimate of a compiled module (see module doc)."""
    module = parse_module(hlo_text)
    aliases = parse_input_output_aliases(hlo_text)

    computations: dict[str, ComputationEstimate] = {}
    for name, comp in module.computations.items():
        if comp.is_entry:
            continue
        kind = _ROLE_KIND.get(module.roles.get(name, ""), "computation")
        computations[name] = _estimate_computation(comp, kind=kind)

    # While/conditional bodies allocate their internal temporaries while
    # the parent is parked on the while/conditional instruction; surface
    # them at that program point (carry/operand bytes are already the
    # parent's buffers — subtract the body's parameters).
    extra_at: dict[int, int] = {}
    for i, instr in enumerate(module.entry.instructions):
        if instr.opcode not in ("while", "conditional"):
            continue
        extra = 0
        for callee in instr.called:
            est = computations.get(callee)
            if est is not None:
                extra = max(
                    extra,
                    est.peak_live_bytes - est.parameter_bytes,
                )
        if extra > 0:
            extra_at[i] = extra_at.get(i, 0) + extra

    entry_raw = _estimate_computation(
        module.entry, kind="entry", extra_at=extra_at
    )
    entry_credited = _estimate_computation(
        module.entry, kind="entry", alias_entries=aliases,
        extra_at=extra_at,
    )
    parameters = {
        i.param_number: HloParameter(
            name=i.name, shape=i.shape, bytes=i.bytes
        )
        for i in module.entry.instructions
        if i.opcode == "parameter" and i.param_number is not None
    }
    return MemoryEstimate(
        entry=entry_credited,
        raw_peak_bytes=entry_raw.peak_live_bytes,
        alias_saved_bytes=(
            entry_raw.peak_live_bytes - entry_credited.peak_live_bytes
        ),
        parameters=parameters,
        aliased_params=frozenset(e.param_number for e in aliases),
        computations=computations,
    )
