"""Static FLOPs / HBM-traffic / wire-bytes costing over optimized HLO.

PR 15 made *memory* static and contractual (analysis/memory.py pins
peak-live bytes per registry program); this module does the same for
*throughput*. Every perf win since PR 3 — prefetch windows, bucketed
reduce-scatter, the paged pool, int8 pages, speculative verify — is at
bottom a claim about three per-step quantities:

- **FLOPs** executed (compute-bound ceiling),
- **HBM bytes moved** (bandwidth-bound ceiling),
- **collective wire bytes** (the ICI term multi-chip projections price).

All three are derivable from the scheduled HLO text the audit pass
already parses, so a regression that doubles a matmul, upcasts the int8
pool, or un-coalesces a bucketed collective moves a pinned number
loudly in CI — no hardware in the loop.

Cost model (and its honest limits):

- **FLOPs**: ``dot``/``convolution`` count contraction math
  (2 x output elements x contracted elements); reduce-class ops
  (``reduce``, ``reduce-window``, ``scatter``, ``select-and-scatter``,
  ``sort``) count their largest operand (a reduction touches every
  input element once); every other arithmetic op counts its output
  elements (one FLOP per element — transcendentals undercount, but the
  pinned ceilings are contracts, not cycle counts); data movement
  (copies, slices, gathers, converts, collectives) counts zero.
- **HBM bytes**: operand bytes + output bytes per instruction,
  dtype-aware via ``memory.shape_bytes`` (an int8 page pool shows its
  real 0.3125x traffic). Fusions count ONCE at the fusion boundary —
  internal producers never materialize. Views (``get-tuple-element``,
  ``bitcast``, ``tuple``) and parameters/constants move nothing at
  their own program point. In-place ``dynamic-update-slice`` is
  deliberately over-counted at destination size (a monotone proxy,
  same stance as the liveness scan).
- **Loop scoping**: a ``while`` contributes its body + condition cost
  multiplied by the static trip count XLA recorded
  (``backend_config={"known_trip_count":...}`` — present on every
  registry program's loops). A while with NO derivable trip count is
  counted ONCE and reported loudly (``unknown_trip_whiles`` /
  ``lower_bound``): the estimate becomes a lower bound, never a
  silently-dropped loop. ``conditional`` takes the max over branches.
- **Wire bytes** (per participating chip, ring accounting — the same
  convention as ``profiling/comm_model``, cross-checked in
  tests/test_cost_analysis.py): with group size N and payload B,
  all-gather / reduce-scatter / all-to-all move B x (N-1)/N, an
  all-reduce moves 2 x B x (N-1)/N (reduce-scatter + all-gather), a
  collective-permute / broadcast moves B. Group size comes from the
  instruction's ``replica_groups`` (explicit or iota form); a
  single-member group — a mesh=1 collective — moves ZERO bytes.

What this is NOT: a cycle-accurate simulator. The numbers feed two
consumers: the pinned ``CostBudget`` ceilings (exact, frozen, loud) and
the roofline projection (``project_step_time`` — max of compute-bound
and bandwidth-bound time at a configurable ``RooflineSpec``, with the
wire term exposed or overlapped per the program's
``CollectiveBudget.async_min_compute`` contract). Real step time on real
hardware sits above both; the projection is the hardware-independent
floor that turns "tok/s regressed" into "which of the three resources
grew".
"""

from __future__ import annotations

import dataclasses
import re

from pytorch_distributed_tpu.analysis.hlo import HLO_COLLECTIVES
from pytorch_distributed_tpu.analysis.memory import (
    HloComputation,
    HloModule,
    parse_module,
    shape_bytes,
    shape_dims,
    shape_elements,
)

# Ops that neither compute nor move bytes at their own program point:
# metadata, views, and buffer-table bookkeeping.
_FREE_OPCODES = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "opt-barrier",
    "rng-get-and-update-state", "get-dimension-size",
})

# Pure data movement: bytes count, FLOPs do not. (convert IS bandwidth —
# the int8 dequant read — but no math in the roofline sense.)
_MOVE_OPCODES = frozenset({
    "copy", "copy-start", "copy-done", "reshape", "broadcast",
    "transpose", "slice", "dynamic-slice", "dynamic-update-slice",
    "concatenate", "gather", "pad", "reverse", "iota", "convert",
    "bitcast-convert", "real", "imag", "custom-call", "infeed",
    "outfeed", "send", "send-done", "recv", "recv-done", "domain",
})

# Reduction-class ops: FLOPs at the largest operand (every input element
# participates once), not the (much smaller) output.
_REDUCE_OPCODES = frozenset({
    "reduce", "reduce-window", "scatter", "select-and-scatter", "sort",
})

_TRIP_COUNT_RE = re.compile(r'"known_trip_count":\{"n":"(\d+)"\}')
_CONTRACT_DIMS_RE = re.compile(r"lhs_contracting_dims=\{([\d,\s]*)\}")
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,\s]*)\}")
_REPLICA_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")


def _collective_base(opcode: str) -> str | None:
    """Base collective opcode for an instruction opcode, or None.
    ``-start`` forms count (they carry the payload); ``-done`` forms do
    not (their traffic was counted at the start)."""
    for base in sorted(HLO_COLLECTIVES, key=len, reverse=True):
        if opcode == base or opcode == base + "-start":
            return base
        if opcode == base + "-done":
            return None
    return None


def _is_collective(opcode: str) -> bool:
    return any(
        opcode == b or opcode == b + "-start" or opcode == b + "-done"
        for b in HLO_COLLECTIVES
    )


def group_size(attrs: str, default: int = 1) -> int:
    """Participant count of a collective from its ``replica_groups``
    attribute: explicit ``{{0,1,2,3}, ...}`` (size of the first group —
    XLA requires uniform groups) or iota ``[G,S]<=[T]`` (S). ``default``
    (the module's num_partitions) covers the
    all-devices-implicit ``replica_groups={}`` form."""
    m = _REPLICA_GROUPS_RE.search(attrs)
    if m:
        ids = [p for p in m.group(1).split(",") if p.strip()]
        return max(1, len(ids))
    m = _REPLICA_GROUPS_IOTA_RE.search(attrs)
    if m:
        return max(1, int(m.group(2)))
    return max(1, default)


def collective_wire_bytes(
    base: str, payload_bytes: int, n: int
) -> int:
    """Per-chip ring-transfer bytes of one collective instruction.

    ``payload_bytes``: the full (unsharded-along-the-collective) tensor
    bytes — output for gather-like ops, operand for reduce-scatter.
    A single-member group (n == 1) moves nothing.
    """
    if n <= 1:
        return 0
    frac = (n - 1) / n
    if base == "all-reduce":
        return int(2 * payload_bytes * frac)
    if base in ("all-gather", "all-to-all", "ragged-all-to-all",
                "reduce-scatter"):
        return int(payload_bytes * frac)
    if base in ("collective-permute", "collective-broadcast"):
        return int(payload_bytes)
    return int(payload_bytes * frac)


def _dot_flops(instr) -> int:
    """2 x output elements x contracted elements, from the inline lhs
    operand type + ``lhs_contracting_dims``. Falls back to output
    elements when the dump omits either (never silently zero)."""
    out = shape_elements(instr.shape)
    m = _CONTRACT_DIMS_RE.search(instr.attrs)
    lhs_dims = (
        shape_dims(instr.operand_shapes[0])
        if instr.operand_shapes else None
    )
    if not m or lhs_dims is None:
        return 2 * out
    contracted = 1
    for idx in (int(p) for p in m.group(1).split(",") if p.strip()):
        if 0 <= idx < len(lhs_dims):
            contracted *= lhs_dims[idx]
    return 2 * out * contracted


_WINDOW_SIZE_RE = re.compile(r"window=\{[^}]*size=([x\d]+)")


def _conv_flops(instr) -> int:
    """2 x output elements x window elements x input features — a
    coarse but monotone convolution count (none of the registry models
    convolve; kept for completeness)."""
    out = shape_elements(instr.shape)
    m = _WINDOW_SIZE_RE.search(instr.attrs)
    window = 1
    if m:
        for d in m.group(1).split("x"):
            if d.strip():
                window *= int(d)
    return 2 * out * window


@dataclasses.dataclass(frozen=True)
class ComputationCost:
    """Aggregate cost of one computation (loop multipliers applied to
    everything it transitively calls)."""

    name: str
    flops: int
    hbm_bytes: int
    wire_bytes: int
    # base collective opcode -> wire bytes attributed to it
    wire_by_collective: dict[str, int]
    # while-instruction names (qualified comp/instr) whose trip count
    # could not be derived: their bodies were counted ONCE.
    unknown_trip_whiles: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """Static per-step cost of one compiled module (per chip)."""

    flops: int
    hbm_bytes: int
    wire_bytes: int
    wire_by_collective: dict[str, int]
    unknown_trip_whiles: tuple[str, ...]
    num_partitions: int
    entry: ComputationCost

    @property
    def lower_bound(self) -> bool:
        """True when an unknown-trip-count while made this estimate a
        lower bound (loud, never silently dropped)."""
        return bool(self.unknown_trip_whiles)

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per HBM byte — the roofline x-axis."""
        return self.flops / self.hbm_bytes if self.hbm_bytes else 0.0


def _merge_wire(into: dict[str, int], frm: dict[str, int], mult: int = 1):
    for k, v in frm.items():
        into[k] = into.get(k, 0) + v * mult


def estimate_cost(hlo_text: str) -> ProgramCost:
    """Walk a compiled module's scheduled HLO and price it (module doc)."""
    module = parse_module(hlo_text)
    default_n = 1
    m = _NUM_PARTITIONS_RE.search(module.header)
    if m:
        default_n = int(m.group(1))
    memo: dict[str, ComputationCost] = {}
    cost = _computation_cost(
        module.entry, module, memo, default_n, stack=frozenset()
    )
    return ProgramCost(
        flops=cost.flops,
        hbm_bytes=cost.hbm_bytes,
        wire_bytes=cost.wire_bytes,
        wire_by_collective=dict(cost.wire_by_collective),
        unknown_trip_whiles=cost.unknown_trip_whiles,
        num_partitions=default_n,
        entry=cost,
    )


def _callee(module: HloModule, name: str) -> HloComputation | None:
    return module.computations.get(name)


def _computation_cost(
    comp: HloComputation,
    module: HloModule,
    memo: dict[str, ComputationCost],
    default_n: int,
    stack: frozenset,
) -> ComputationCost:
    if comp.name in memo:
        return memo[comp.name]
    if comp.name in stack:  # defensive: HLO call graphs are acyclic
        return ComputationCost(comp.name, 0, 0, 0, {}, ())
    stack = stack | {comp.name}

    flops = 0
    hbm = 0
    wire = 0
    wire_by: dict[str, int] = {}
    unknown: list[str] = []

    def sub(name: str) -> ComputationCost:
        callee = _callee(module, name)
        if callee is None:
            return ComputationCost(name, 0, 0, 0, {}, ())
        return _computation_cost(callee, module, memo, default_n, stack)

    for instr in comp.instructions:
        op = instr.opcode
        if op in _FREE_OPCODES:
            continue
        operand_bytes = sum(
            shape_bytes(s) for s in instr.operand_shapes
        )
        boundary_bytes = operand_bytes + instr.bytes

        if op == "fusion" or op == "call":
            # Boundary counting: bytes at the fusion's operands/output
            # only; FLOPs (and any nested loops) from the body.
            inner = sub(instr.called[0]) if instr.called else None
            hbm += boundary_bytes
            if inner is not None:
                flops += inner.flops
                wire += inner.wire_bytes
                _merge_wire(wire_by, inner.wire_by_collective)
                unknown.extend(inner.unknown_trip_whiles)
            continue

        if op == "while":
            tm = _TRIP_COUNT_RE.search(instr.attrs)
            trips = int(tm.group(1)) if tm else None
            body = cond = None
            for nm in instr.called:
                role = module.roles.get(nm, "")
                if role == "body":
                    body = sub(nm)
                elif role == "condition":
                    cond = sub(nm)
            mult = trips if trips is not None else 1
            if trips is None:
                unknown.append(f"{comp.name}/{instr.name}")
            for part in (body, cond):
                if part is None:
                    continue
                flops += part.flops * mult
                hbm += part.hbm_bytes * mult
                wire += part.wire_bytes * mult
                _merge_wire(wire_by, part.wire_by_collective, mult)
                unknown.extend(part.unknown_trip_whiles)
            # The carry iterates in place; the while instruction itself
            # moves nothing beyond what the body already counted.
            continue

        if op == "conditional":
            # Upper bound: the most expensive branch, plus the
            # predicate/operand handoff once.
            branches = [sub(nm) for nm in instr.called]
            hbm += boundary_bytes
            if branches:
                worst = max(branches, key=lambda c: c.flops + c.hbm_bytes)
                flops += worst.flops
                hbm += worst.hbm_bytes
                wire += worst.wire_bytes
                _merge_wire(wire_by, worst.wire_by_collective)
                for b in branches:
                    unknown.extend(b.unknown_trip_whiles)
            continue

        if _is_collective(op):
            base = _collective_base(op)
            if base is not None:
                # Payload: the full tensor on the wire — the operand for
                # reduce-scatter (output is the 1/N shard), the output
                # for everything else (gathers inflate, reduces match).
                payload = (
                    operand_bytes if base == "reduce-scatter"
                    else instr.bytes
                )
                n = group_size(instr.attrs, default=default_n)
                w = collective_wire_bytes(base, payload, n)
                wire += w
                wire_by[base] = wire_by.get(base, 0) + w
                hbm += boundary_bytes
            continue

        hbm += boundary_bytes
        if op in _MOVE_OPCODES:
            continue
        if op == "dot":
            flops += _dot_flops(instr)
        elif op == "convolution":
            flops += _conv_flops(instr)
        elif op in _REDUCE_OPCODES:
            flops += max(
                [shape_elements(s) for s in instr.operand_shapes]
                or [shape_elements(instr.shape)]
            )
        else:
            flops += shape_elements(instr.shape)

    result = ComputationCost(
        name=comp.name,
        flops=flops,
        hbm_bytes=hbm,
        wire_bytes=wire,
        wire_by_collective=wire_by,
        unknown_trip_whiles=tuple(unknown),
    )
    memo[comp.name] = result
    return result


# ---------------------------------------------------------------------------
# Roofline projection
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RooflineSpec:
    """Chip constants the roofline prices a ProgramCost at.

    Public-spec assumptions, not measurements (same stance as
    ``profiling/comm_model.ChipSpec`` — v5e: 197 TFLOP/s bf16, ~819 GB/s
    HBM, conservative 45 GB/s per-chip effective collective
    throughput). Pass your own spec for another chip or a measured rig.
    """

    name: str
    peak_flops: float
    hbm_bytes_per_s: float
    ici_bytes_per_s: float


V5E_ROOFLINE = RooflineSpec(
    name="v5e",
    peak_flops=197e12,
    hbm_bytes_per_s=819e9,
    ici_bytes_per_s=45e9,
)


def project_step_time(
    cost: ProgramCost,
    spec: RooflineSpec = V5E_ROOFLINE,
    *,
    overlapped_comm: bool = False,
) -> dict:
    """Roofline step-time projection: max of the compute-bound and
    bandwidth-bound times, with the collective wire term either hidden
    under them (``overlapped_comm=True`` — the program carries an
    ``async_min_compute`` overlap contract) or fully exposed
    (serialised on top — no contract, no benefit of the doubt).

    Returns the projected seconds, the per-resource times, which
    resource binds, and the spec's ridge intensity (FLOP/byte at which
    compute and bandwidth bound times cross).
    """
    t_compute = cost.flops / spec.peak_flops
    t_hbm = cost.hbm_bytes / spec.hbm_bytes_per_s
    t_wire = cost.wire_bytes / spec.ici_bytes_per_s
    on_chip = max(t_compute, t_hbm)
    step = max(on_chip, t_wire) if overlapped_comm else on_chip + t_wire
    if t_wire > on_chip:
        bound = "wire"
    elif t_compute >= t_hbm:
        bound = "compute"
    else:
        bound = "bandwidth"
    return {
        "spec": spec.name,
        "projected_step_s": step,
        "compute_s": t_compute,
        "hbm_s": t_hbm,
        "wire_s": t_wire,
        "wire_overlapped": overlapped_comm,
        "bound": bound,
        "arithmetic_intensity": cost.arithmetic_intensity,
        "ridge_intensity": spec.peak_flops / spec.hbm_bytes_per_s,
        "lower_bound": cost.lower_bound,
    }


def projected_tok_s(
    cost: ProgramCost,
    tokens_per_step: int,
    spec: RooflineSpec = V5E_ROOFLINE,
    *,
    overlapped_comm: bool = False,
) -> float:
    """Tokens/s the roofline projects for a decode-step program that
    advances ``tokens_per_step`` tokens per dispatch (active rows x
    tokens-per-tick) — the number scripts/decode_bench.py prints next
    to the measured rate so projection drift stays visible."""
    proj = project_step_time(cost, spec, overlapped_comm=overlapped_comm)
    step = proj["projected_step_s"]
    return tokens_per_step / step if step > 0 else 0.0
