"""Pytest fixture for one-line program audits in parallelism tests.

Usage (tests/conftest.py re-exports the fixture):

    def test_my_strategy(audit):
        step, args = build_step(...)
        audit.assert_clean(step, args, expected_budget(mcfg, cfg))

or, when the test wants the report itself:

    report = audit(step, args, budget)
    assert report.clean(), report.table()
"""

from __future__ import annotations

import pytest

from pytorch_distributed_tpu.analysis.audit import audit_program
from pytorch_distributed_tpu.analysis.report import AuditReport


class ProgramAuditor:
    """Callable wrapper over audit_program with an assertion helper."""

    def __call__(self, fn, args, budget=None, **kwargs) -> AuditReport:
        return audit_program(fn, args, budget, **kwargs)

    def assert_clean(self, fn, args, budget=None, **kwargs) -> AuditReport:
        report = audit_program(fn, args, budget, **kwargs)
        assert report.clean(), "\n" + report.table()
        return report


@pytest.fixture
def audit() -> ProgramAuditor:
    return ProgramAuditor()
