"""Optimized-HLO text parsing for the audit pass.

XLA's compiled-module text is the ground truth for what a step actually
does on device: the collective instructions it lists are exactly the op
names a profiler trace row carries (pinned by tests/test_hlo_collectives.py),
and the module header records the input/output buffer aliasing that
donation (``donate_argnums``) negotiated with the compiler. This module
extracts both without running the program.

Scope note: dtype analysis does NOT live here. XLA:CPU legalises bf16
dots into convert+f32-dot pairs during optimization, so optimized HLO on
the CPU test rig misreports the program's numerics; dtype/convert checks
run on the jaxpr instead (analysis/jaxpr_scan.py), which is
platform-independent.
"""

from __future__ import annotations

import dataclasses
import re

# Every HLO collective opcode (base form; XLA also emits async -start/-done
# pairs whose instruction names contain the base).
HLO_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
    "ragged-all-to-all",
)

_INSTR_RE = re.compile(r"\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=")

# Longest opcode first: \b matches after a hyphen, so "all-to-all" would
# otherwise claim "ragged-all-to-all" instructions before the ragged
# pattern gets a look.
_COLLECTIVES_LONGEST_FIRST = sorted(HLO_COLLECTIVES, key=len, reverse=True)


def collective_instructions(hlo_text: str) -> dict[str, list[str]]:
    """{base_opcode: [instruction names]} for every collective instruction
    in the compiled module text.

    Instruction names (the left-hand side of each ``name = type op(...)``
    line) are the strings that appear on profiler device tracks, so the
    caller can cross-check them against trace classification
    (profiling.trace_analysis.classify_op).
    """
    found: dict[str, list[str]] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        rhs = line[m.end():]
        for op in _COLLECTIVES_LONGEST_FIRST:
            if re.search(rf"\b{op}(?:-start|-done)?\(", rhs):
                found.setdefault(op, []).append(m.group(1))
                break
    return found


def collective_counts(hlo_text: str) -> dict[str, int]:
    """{base_opcode: instruction count} (convenience over
    collective_instructions)."""
    return {
        op: len(names)
        for op, names in collective_instructions(hlo_text).items()
    }


# Opcodes that count as "compute scheduled between" an async collective's
# start and done: post-optimization XLA keeps real math inside fusions
# (plus the occasional unfused dot/convolution), custom-calls (Pallas
# kernels), and nested loops. Everything else between a start/done pair —
# tuples, bitcasts, copies, other collectives — is bookkeeping that hides
# nothing.
_COMPUTE_OPCODES = ("fusion", "dot", "convolution", "custom-call", "while")

_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def _first_operand(rhs_from_opcode: str) -> str | None:
    """First operand name of ``opcode(...)``. Operand lists interleave
    inline types with %-prefixed names (``done((f32[8], f32[64])
    %start.1)``), so the name is the first %-token after the opcode's
    paren; dumps without % prefixes fall back to the first bare token
    that is not a shape (no '[')."""
    open_idx = rhs_from_opcode.find("(")
    if open_idx < 0:
        return None
    body = rhs_from_opcode[open_idx + 1:]
    m = _OPERAND_NAME_RE.search(body)
    if m:
        return m.group(1)
    for token in re.split(r"[(),\s]+", body):
        if token and "[" not in token and "{" not in token:
            return token
    return None


@dataclasses.dataclass(frozen=True)
class AsyncCollective:
    """One async collective start/done pair in a compiled module, with the
    number of compute instructions the schedule placed between them."""

    opcode: str  # base opcode, e.g. "all-gather"
    start: str  # instruction name of the -start
    done: str  # instruction name of the -done
    compute_between: int


def async_collective_pairs(hlo_text: str) -> list[AsyncCollective]:
    """Every ``<op>-start`` / ``<op>-done`` pair in the module, paired by
    the done's first operand, with the count of compute instructions
    (``_COMPUTE_OPCODES``) scheduled between them.

    Post-scheduling HLO text lists each computation's instructions in
    execution order, so "instructions between start and done" IS the work
    the latency-hiding scheduler found to overlap with the collective:
    ``compute_between == 0`` means the transfer is async in name only —
    its full latency is exposed. Backends that emit synchronous
    collectives (XLA:CPU) produce no pairs at all; callers must treat an
    empty result as "nothing to check", not "all overlapped".
    """
    pending: dict[str, tuple[str, str, int]] = {}  # start name -> state
    pairs: list[AsyncCollective] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rhs = m.group(1), line[m.end():]
        matched = None
        for op in _COLLECTIVES_LONGEST_FIRST:
            sm = re.search(rf"\b{op}(-start|-done)?\(", rhs)
            if sm:
                matched = (op, sm.group(1))
                break
        if matched:
            op, kind = matched
            if kind == "-start":
                pending[name] = (op, name, 0)
            elif kind == "-done":
                start_name = _first_operand(rhs[sm.start():])
                state = pending.pop(start_name, None)
                if state is not None:
                    pairs.append(
                        AsyncCollective(
                            opcode=state[0],
                            start=state[1],
                            done=name,
                            compute_between=state[2],
                        )
                    )
            # A sync collective (or another collective's start/done)
            # between a pair does not count as compute.
            continue
        is_compute = any(
            re.search(rf"\b{op}\(", rhs) for op in _COMPUTE_OPCODES
        )
        if is_compute and pending:
            pending = {
                k: (op, s, n + 1) for k, (op, s, n) in pending.items()
            }
    return pairs


@dataclasses.dataclass(frozen=True)
class AliasEntry:
    """One input->output buffer alias from the HLO module header."""

    output_index: tuple[int, ...]
    param_number: int
    param_index: tuple[int, ...]
    kind: str  # "may-alias" | "must-alias"


# Header syntax: input_output_alias={ {0}: (0, {}, may-alias), {1}: (3, {1}) }
_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{([\d,\s]*)\}"
    r"(?:,\s*(may-alias|must-alias))?\)"
)


def _index_tuple(s: str) -> tuple[int, ...]:
    return tuple(int(p) for p in s.split(",") if p.strip())


def _alias_block(header: str) -> str | None:
    """The balanced-brace body of ``input_output_alias={...}`` (the map
    nests braces for output/param ShapeIndexes, so a regex can't scan it)."""
    key = "input_output_alias={"
    start = header.find(key)
    if start < 0:
        return None
    depth, i = 1, start + len(key)
    while i < len(header) and depth:
        if header[i] == "{":
            depth += 1
        elif header[i] == "}":
            depth -= 1
        i += 1
    return header[start + len(key): i - 1]


def parse_input_output_aliases(hlo_text: str) -> list[AliasEntry]:
    """Donated-buffer aliases the compiler ACCEPTED, from the HloModule
    header. Empty list means no donation survived compilation (either the
    jit had no donate_argnums or XLA rejected every alias)."""
    header = hlo_text.splitlines()[0] if hlo_text else ""
    block = _alias_block(header)
    if block is None:
        return []
    return [
        AliasEntry(
            output_index=_index_tuple(e.group(1)),
            param_number=int(e.group(2)),
            param_index=_index_tuple(e.group(3)),
            kind=e.group(4) or "may-alias",
        )
        for e in _ALIAS_ENTRY_RE.finditer(block)
    ]


def aliased_param_numbers(hlo_text: str) -> set[int]:
    """Entry-parameter numbers with at least one accepted output alias."""
    return {e.param_number for e in parse_input_output_aliases(hlo_text)}
