"""vma-check: static replication/varying-axes checker for shard_map bodies.

The rig's jax predates the varying-manual-axes (vma) type system, so
``utils/compat.py`` maps ``check_vma=True`` onto the UNCHECKED
``check_rep=False`` — a missing ``psum`` (the cross-device divergence DDP's
reducer exists to prevent) would train silently wrong. This module is our
own replication checker, independent of the jax version: an abstract
interpreter over the jaxpr of every ``shard_map`` body that propagates a
per-value *varying axes* lattice through each equation.

Lattice: each value maps to the ``frozenset`` of mesh axis names it may
vary over (devices along that axis may hold DIFFERENT values). Join is
set union; the interpretation is a forward may-analysis, so a reported
invariant value really is replicated, while a reported varying value is
only *possibly* varying (the safe direction for a race detector).

Transfer rules:

- shard_map inputs start varying over exactly the axes their ``in_specs``
  shard them over (a replicated input is the same on every device);
- elementwise/dot/reshape/... (any unhandled primitive): output joins the
  operands' vma;
- ``psum``/``pmax``/``pmin`` over axes A: the reduction makes the result
  identical along A — vma := vma - A. Reducing a value already invariant
  over an axis is a *redundant collective* (wasted bandwidth, rule 3);
- ``all_gather``/``psum_scatter``(``reduce_scatter``)/``ppermute``/
  ``all_to_all`` over axes A: result stays (or becomes) device-dependent —
  vma := vma | A. This matches jax's typed semantics, where a tiled
  all_gather output is still *typed* varying even though it is numerically
  replicated (see parallel/zero.unscatter for why the repo psums instead
  of gathering where an invariant type is needed);
- ``axis_index`` over axis a: varying over {a} by construction;
- ``pvary``/``pcast`` over axes A (post-vma jax only; the pre-vma shims
  are identity and leave no equation behind): vma := vma | A, and casting
  an already-varying axis is flagged (rule 4);
- ``scan``/``while``: body interpreted to a fixpoint on the carry vmas
  (a fresh zeros accumulator starts invariant and is joined with whatever
  the body feeds back). A varying while-predicate joins into every carry
  (devices may disagree on the trip count);
- ``cond``/``switch``: outputs join across all branches AND the predicate
  (devices taking different branches produce device-dependent results);
- call-like primitives (pjit, remat, custom_jvp/vjp bodies): interpreted
  through, positionally.

Reported findings (``checker="vma"``):

- ``missing-psum`` (error, rule 2) — a value flows into an out_spec that
  declares it REPLICATED (no mesh axes) while the interpreter infers it
  varying: the missing-reduction bug. Loss/metric logging, optimizer
  scalars, and replicated parameter updates all exit through replicated
  out_specs, so this is exactly "a varying value consumed where
  replication is required".
- ``vma-out-spec-mismatch`` (error, rule 1) — a SHARDED out_spec whose
  axes disagree with the inferred vma (varying over an axis the spec does
  not shard over): each device writes its own value into a slot the
  program's type says is consistent — a silent cross-device race.
- ``divergent-collective`` (error) — a collective over axis a inside a
  cond branch / while body whose predicate varies over a: peers along a
  disagree on whether to rendezvous (deadlock, or a mismatched exchange).
  This machine-checks the uniform-collective contract the 1F1B pipeline
  documents (parallel/pipeline.py). The finding carries ``via`` detail
  distinguishing the two routes in: ``cond-branch`` (devices take
  different branches) and ``while-trip-count`` (devices run the loop a
  different number of times). The trip-count route is how DECODE
  SAMPLING breaks programs: a generation/verify loop advanced by a
  sampled token or a speculative accept length (the serving engines'
  traced-trip-count decode loops, models/speculative.py's verify loop)
  diverges when the sampled value derives from logits that were never
  psum-replicated — each shard then iterates a different number of
  times and the next iteration's in-body psums deadlock. The fixpoint
  carry propagation is what catches it: the sampled value reaches the
  predicate only through the carry, so the divergence is invisible on
  the first pass (pinned in tests/test_analysis.py).
- ``redundant-collective`` (warn, rule 3) — psum/pmax/pmin over an axis
  the operand is already invariant on (literal operands are exempt: the
  ``psum(1, axis)`` axis-size idiom reduces a constant on purpose).
- ``redundant-pvary`` (warn, rule 4) — pvary/pcast of a value already
  varying over the requested axes.

Known false-negative classes (documented in docs/ANALYSIS.md): on pre-vma
jax the pcast/pvary shims are identity, so rule 4 only engages on post-vma
jaxprs; primitives with sub-jaxprs the interpreter cannot map positionally
fall back to the conservative join (over-approximating vma never hides a
race, but the body's internal findings are skipped — counted in
``summary["opaque"]``). Grouped collectives (``axis_index_groups``) are
typed as still-varying over their axes — a grouped psum replicates only
within each group, so treating it as the full axis (the old behaviour)
would hide cross-group out_spec races; a later full-axis psum is what
discharges the varying bit.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any

try:  # jax >= 0.4.16 public core surface
    from jax.extend.core import Literal  # type: ignore
except ImportError:  # pragma: no cover
    from jax.core import Literal  # type: ignore

from pytorch_distributed_tpu.analysis.report import Finding

# Collectives that REDUCE along their axes: result is identical on every
# member of the axis afterwards (varying -> invariant).
_REDUCE_PRIMS = frozenset({"psum", "pmax", "pmin"})
# Collectives whose result is (still) device-dependent along their axes.
_VARYING_PRIMS = frozenset(
    {"all_gather", "reduce_scatter", "ppermute", "pshuffle", "all_to_all",
     "ragged_all_to_all"}
)
# vma casts (post-vma jax only; identity shims on pre-vma leave no eqn).
_PVARY_PRIMS = frozenset({"pvary", "pcast"})
_COLLECTIVE_PRIMS = _REDUCE_PRIMS | _VARYING_PRIMS
# Fixpoint bound: the lattice is finite (subsets of the mesh axes) and the
# transfer is monotone, so carries converge in <= |axes| joins per carry;
# this is a safety net, not a tuning knob.
_FIXPOINT_LIMIT = 16


def _axis_names(params: dict) -> tuple[str, ...]:
    """String mesh-axis names of a collective eqn (psum's ``axes`` may mix
    in positional-int axes from vmap; those are not mesh axes)."""
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        return ()
    if isinstance(raw, str):
        return (raw,)
    try:
        return tuple(a for a in raw if isinstance(a, str))
    except TypeError:  # a single non-str, non-iterable name object
        return ()


def _spec_axes(entry: Any) -> frozenset:
    """Mesh axes named by one in_names/out_names entry.

    shard_map (pre- and post-vma) carries ``{dim: (axis, ...)}`` dicts;
    PartitionSpec entries are tolerated for forward-compatibility."""
    if entry is None:
        return frozenset()
    if hasattr(entry, "items"):  # {dim: (axes...)} — the shard_map form
        out: set = set()
        for axes in entry.values():
            if isinstance(axes, (tuple, list)):
                out.update(a for a in axes if isinstance(a, str))
            elif isinstance(axes, str):
                out.add(axes)
        return frozenset(out)
    out = set()
    for e in entry:  # PartitionSpec-like
        if isinstance(e, str):
            out.add(e)
        elif isinstance(e, (tuple, list)):
            out.update(a for a in e if isinstance(a, str))
    return frozenset(out)


def _sub_jaxpr(val: Any):
    """The bare jaxpr inside a param value (ClosedJaxpr or bare), or None.

    ClosedJaxpr must be unwrapped FIRST: it forwards ``.eqns`` but not
    ``.invars``/``.outvars``, which the interpreter needs."""
    inner = getattr(val, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    if hasattr(val, "eqns") and hasattr(val, "invars"):
        return val
    return None


def _call_body(eqn) -> Any | None:
    """For call-like primitives (pjit, remat, custom_jvp/vjp, named_call):
    the single body jaxpr whose invars map positionally onto the eqn's."""
    bodies = []
    for key, val in eqn.params.items():
        if key == "branches":
            return None  # cond — handled structurally
        sub = _sub_jaxpr(val)
        if sub is not None:
            bodies.append(sub)
    if len(bodies) == 1 and len(bodies[0].invars) == len(eqn.invars):
        return bodies[0]
    return None


@dataclasses.dataclass
class VmaResult:
    """Interpretation result for one shard_map body."""

    findings: list[Finding]
    out_vmas: list[frozenset]
    opaque: Counter  # primitive name -> times conservatively joined


class VmaInterpreter:
    """Forward abstract interpreter for the varying-axes lattice."""

    def __init__(self) -> None:
        self.findings: list[Finding] = []
        self.opaque: Counter = Counter()

    # -- helpers ----------------------------------------------------------
    def _finding(self, code, severity, message, **detail) -> None:
        self.findings.append(
            Finding(
                checker="vma", code=code, severity=severity,
                message=message, detail=detail,
            )
        )

    def _check_divergence(self, eqn, axes, divergent, record) -> None:
        """``divergent`` maps each divergent axis to HOW control flow
        diverged over it: ``cond-branch`` (devices take different
        branches) or ``while-trip-count`` (devices run the loop a
        different number of times — the decode-sampling hazard: a
        speculative verify loop whose accept length derives from
        NON-reduced logits gives every shard its own trip count, and
        the next iteration's psums deadlock). The finding names the
        route so the fix is obvious: gate the RESULT for a branch,
        reduce the sampled value feeding the predicate for a trip
        count."""
        clash = set(axes) & set(divergent)
        if clash and record:
            vias = sorted({divergent[a] for a in clash})
            how = (
                "a while loop whose TRIP COUNT varies over the same "
                "axis/axes (each device iterates a different number of "
                "times — e.g. a decode loop advanced by a sampled "
                "accept length that was never psum-replicated)"
                if vias == ["while-trip-count"]
                else "control flow whose predicate varies over the "
                     "same axis/axes"
            )
            self._finding(
                "divergent-collective", "error",
                f"{eqn.primitive.name} over {sorted(clash)} executes "
                f"under {how}: peers disagree on whether to communicate "
                "(deadlock or mismatched exchange); hoist the collective "
                "out of the divergent region and gate its RESULT — or, "
                "for a sampling-driven trip count, reduce the value "
                "feeding the predicate first",
                primitive=eqn.primitive.name, axes=sorted(clash),
                via=vias,
            )

    # -- interpretation ---------------------------------------------------
    #
    # Each value is tracked as ``(vma, const)``: the varying-axes set plus
    # a constant-provenance bit (derived ONLY from literals / no-input
    # primitives like iota). The const bit exempts trace-time-constant
    # chains from the redundant-collective rule: ``psum(1, axis)`` is the
    # axis-size idiom, and jax 0.4's AD transposes a differentiated
    # forward psum into ``psum(<literal cotangent seed>)`` (the pipeline
    # loss psum) — neither is a redundancy bug a human should fix.

    def interpret(
        self,
        jaxpr,
        in_vmas,
        *,
        record: bool = True,
        divergent=(),
    ) -> list[frozenset]:
        """vmas of ``jaxpr.outvars`` given vmas of its invars.
        ``divergent`` maps axis name -> divergence route ("cond-branch"
        / "while-trip-count"); a bare axis iterable is accepted and
        treated as cond-branch divergence."""
        if not isinstance(divergent, dict):
            divergent = {a: "cond-branch" for a in divergent}
        outs = self._run(
            jaxpr, [(frozenset(s), False) for s in in_vmas],
            record=record, divergent=divergent,
        )
        return [s for s, _ in outs]

    def _run(self, jaxpr, ins, *, record, divergent):
        env: dict = {}

        def read(v):
            if isinstance(v, Literal):
                return (frozenset(), True)
            return env.get(v, (frozenset(), False))

        for v, s in zip(jaxpr.invars, ins):
            env[v] = s
        for v in getattr(jaxpr, "constvars", ()):
            env[v] = (frozenset(), False)

        for eqn in jaxpr.eqns:
            eqn_ins = [read(v) for v in eqn.invars]
            outs = self._eqn(eqn, eqn_ins, record, divergent)
            for v, s in zip(eqn.outvars, outs):
                env[v] = s
        return [read(v) for v in jaxpr.outvars]

    def _eqn(self, eqn, ins, record, divergent):
        name = eqn.primitive.name
        vmas = [s for s, _ in ins]
        join = frozenset().union(*vmas) if vmas else frozenset()
        all_const = all(c for _, c in ins)  # True when no inputs (iota...)
        n_out = len(eqn.outvars)

        if name in _REDUCE_PRIMS:
            axes = frozenset(_axis_names(eqn.params))
            self._check_divergence(eqn, axes, divergent, record)
            grouped = eqn.params.get("axis_index_groups") is not None
            if grouped:
                # A grouped reduction replicates only WITHIN each group:
                # members of different groups hold different sums, so the
                # result still varies over the named axes. Joining the
                # axes in (instead of subtracting them) keeps a
                # downstream ungrouped-psum requirement live — the old
                # full-axis treatment typed grouped psums as replicated
                # and silently passed out_specs that race across groups.
                # No redundant-collective warn either: invariance over
                # the full axis does not make a WITHIN-group reduction
                # redundant evidence we can judge here.
                return [(s | axes, const) for s, const in ins]
            outs = []
            for v, (s, const) in zip(eqn.invars, ins):
                dead = axes - s
                if dead and record and not const:
                    self._finding(
                        "redundant-collective", "warn",
                        f"{name} over {sorted(dead)} of a value already "
                        "replicated on that axis/axes: every device "
                        "contributes an identical term — the collective "
                        "is wasted bandwidth (or the value upstream was "
                        "MEANT to be varying)",
                        primitive=name, axes=sorted(dead),
                        operand=str(getattr(v, "aval", "")),
                    )
                outs.append((s - axes, const))
            return outs

        if name in _VARYING_PRIMS:
            axes = frozenset(_axis_names(eqn.params))
            self._check_divergence(eqn, axes, divergent, record)
            outs = [(s | axes, const) for s, const in ins][:n_out]
            return outs or [(join | axes, all_const)] * n_out

        if name in _PVARY_PRIMS:
            axes = frozenset(_axis_names(eqn.params))
            outs = []
            for (s, const) in ins:
                already = axes & s
                if already and record:
                    self._finding(
                        "redundant-pvary", "warn",
                        f"{name} over {sorted(already)} of a value already "
                        "varying on that axis/axes: the cast is a no-op "
                        "(post-vma jax rejects it outright) — use "
                        "ops.tp.pvary_missing to cast only missing axes",
                        primitive=name, axes=sorted(already),
                    )
                outs.append((s | axes, const))
            return outs

        if name == "axis_index":
            return [(frozenset(_axis_names(eqn.params)), False)]

        if name == "scan":
            return self._scan(eqn, ins, record, divergent)
        if name == "while":
            return self._while(eqn, ins, record, divergent)
        if name == "cond":
            return self._cond(eqn, ins, record, divergent)
        if name == "shard_map":  # nested manual region: opaque from here
            self.opaque[name] += 1
            return [(join, False)] * n_out

        body = _call_body(eqn)
        if body is not None:
            outs = self._run(body, ins, record=record, divergent=divergent)
            if len(outs) == n_out:
                return outs
            self.opaque[name] += 1
            return [(join, False)] * n_out

        if any(_sub_jaxpr(v) is not None for v in eqn.params.values()):
            # A sub-jaxpr we cannot map positionally: conservative join
            # (may over-approximate varying; never hides a race).
            self.opaque[name] += 1
            return [(join, False)] * n_out
        return [(join, all_const)] * n_out

    @staticmethod
    def _join_carry(carry, outs, extra_vma=frozenset()):
        """Monotone carry update: vma joins UP (union), const meets DOWN
        (and) — both directions converge."""
        return [
            (c | o | extra_vma, cc and oc)
            for (c, cc), (o, oc) in zip(carry, outs)
        ]

    def _scan(self, eqn, ins, record, divergent):
        p = eqn.params
        nc, ncar = p["num_consts"], p["num_carry"]
        body = _sub_jaxpr(p["jaxpr"])
        consts, carry, xs = ins[:nc], list(ins[nc:nc + ncar]), ins[nc + ncar:]
        for _ in range(_FIXPOINT_LIMIT):
            outs = self._run(
                body, consts + carry + xs, record=False, divergent=divergent
            )
            new = self._join_carry(carry, outs[:ncar])
            if new == carry:
                break
            carry = new
        outs = self._run(
            body, consts + carry + xs, record=record, divergent=divergent
        )
        return self._join_carry(carry, outs[:ncar]) + outs[ncar:]

    @staticmethod
    def _diverge(divergent: dict, axes: frozenset, via: str) -> dict:
        """Enter a divergent region: the predicate's axes join the map
        tagged with HOW control flow diverges over them (an axis
        already divergent from an enclosing region keeps its original
        route — the outermost divergence is the one to fix first)."""
        if not axes:
            return divergent
        return {**{a: via for a in axes}, **divergent}

    def _while(self, eqn, ins, record, divergent):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cond_body = _sub_jaxpr(p["cond_jaxpr"])
        loop_body = _sub_jaxpr(p["body_jaxpr"])
        cc, bc, carry = ins[:cn], ins[cn:cn + bn], list(ins[cn + bn:])
        pred = frozenset()
        for _ in range(_FIXPOINT_LIMIT):
            pred = self._run(
                cond_body, cc + carry, record=False, divergent=divergent
            )[0][0]
            outs = self._run(
                loop_body, bc + carry, record=False,
                divergent=self._diverge(divergent, pred,
                                        "while-trip-count"),
            )
            # A varying predicate means devices disagree on the trip
            # count, so every carry is device-dependent afterwards.
            new = self._join_carry(carry, outs, extra_vma=pred)
            if new == carry:
                break
            carry = new
        # Both bodies are checked under the predicate's divergence: with a
        # varying predicate devices disagree on the trip count, so a
        # collective in the COND body (re-entered a different number of
        # times per device) mismatches exactly like one in the loop body.
        # The fixpoint matters for the decode-sampling case: a sampled
        # accept length reaches the predicate only through the carry, so
        # the divergence appears on iteration 2 — the rule covers
        # sampling-driven trip counts, not just syntactically-varying
        # predicates (pinned in tests/test_analysis.py).
        trip_div = self._diverge(divergent, pred, "while-trip-count")
        self._run(cond_body, cc + carry, record=record, divergent=trip_div)
        self._run(loop_body, bc + carry, record=record, divergent=trip_div)
        return carry

    def _cond(self, eqn, ins, record, divergent):
        (pred, pred_const), ops = ins[0], ins[1:]
        branch_div = self._diverge(divergent, pred, "cond-branch")
        branch_outs = []
        for br in eqn.params["branches"]:
            body = _sub_jaxpr(br)
            branch_outs.append(
                self._run(body, ops, record=record, divergent=branch_div)
            )
        return [
            (
                frozenset().union(pred, *(s for s, _ in per_out)),
                pred_const and all(c for _, c in per_out),
            )
            for per_out in zip(*branch_outs)
        ]


# -------------------------------------------------------------- entry API

def find_shard_map_eqns(jaxpr) -> list:
    """Every ``shard_map`` eqn reachable from ``jaxpr`` (closed or bare),
    recursing through sub-jaxprs but not into shard_map bodies themselves
    (nested manual regions would need their own outer-axes context)."""
    from pytorch_distributed_tpu.analysis.jaxpr_scan import _subjaxprs

    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    found: list = []

    def walk(jx) -> None:
        for eqn in jx.eqns:
            if eqn.primitive.name == "shard_map":
                found.append(eqn)
                continue
            for sub in _subjaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return found


def check_shard_map_eqn(eqn) -> VmaResult:
    """Run the vma interpreter over one shard_map eqn's body and diff the
    inferred output vmas against its out_specs."""
    params = eqn.params
    body = _sub_jaxpr(params["jaxpr"])
    in_names = params.get("in_names", params.get("in_specs", ()))
    out_names = params.get("out_names", params.get("out_specs", ()))
    in_vmas = [_spec_axes(n) for n in in_names]

    interp = VmaInterpreter()
    out_vmas = interp.interpret(body, in_vmas, record=True)
    findings = interp.findings

    for i, (vma, names) in enumerate(zip(out_vmas, out_names)):
        expected = _spec_axes(names)
        extra = vma - expected
        if not extra:
            continue
        aval = str(getattr(body.outvars[i], "aval", "?"))
        if not expected:
            findings.append(
                Finding(
                    checker="vma", code="missing-psum", severity="error",
                    message=(
                        f"output {i} ({aval}) is declared REPLICATED by its "
                        f"out_spec but may vary over {sorted(extra)}: a "
                        "reduction (psum/pmean) is missing upstream — each "
                        "device would silently hold a different value "
                        "(loss/metric/weight divergence)"
                    ),
                    detail={"output": i, "aval": aval,
                            "varying": sorted(vma)},
                )
            )
        else:
            findings.append(
                Finding(
                    checker="vma", code="vma-out-spec-mismatch",
                    severity="error",
                    message=(
                        f"output {i} ({aval}) may vary over {sorted(extra)} "
                        f"but its out_spec only shards over "
                        f"{sorted(expected)}: the unsharded axis/axes hold "
                        "device-dependent values the program's type calls "
                        "consistent — a cross-device race"
                    ),
                    detail={"output": i, "aval": aval,
                            "varying": sorted(vma),
                            "out_spec_axes": sorted(expected)},
                )
            )
    return VmaResult(
        findings=findings, out_vmas=out_vmas, opaque=interp.opaque
    )


def check_vma_program(jaxpr):
    """Check every shard_map body in a traced program.

    Returns ``(findings, summary)``; a program with no shard_map regions
    is vacuously clean (the pjit path delegates replication to the SPMD
    partitioner — noted in the summary so a report cannot silently claim
    coverage it did not have).
    """
    eqns = find_shard_map_eqns(jaxpr)
    findings: list[Finding] = []
    opaque: Counter = Counter()
    outputs_checked = 0
    for eqn in eqns:
        result = check_shard_map_eqn(eqn)
        findings.extend(result.findings)
        opaque.update(result.opaque)
        outputs_checked += len(result.out_vmas)
    summary = {
        "shard_map_bodies": len(eqns),
        "outputs_checked": outputs_checked,
        "opaque": dict(opaque),
    }
    return findings, summary
