"""Registered (strategy x model) audit cases.

One place that knows how to build every jitted training entry point on the
virtual-device CPU rig (the same tiny-model constructions
tests/test_hlo_collectives.py compiles), paired with the collective budget
its strategy implies. Consumed by ``scripts/audit.py --all`` and by tests.

Every case builds a REAL step function from the production builders
(train/trainer.py, parallel/explicit.py, parallel/pipeline.py,
parallel/api.py) — the audit runs against the exact programs training
runs, not stand-ins. Each explicit (shard_map) case has a pjit twin so
both placement paths stay audited; the ddp/fsdp budgets carry measured
``max_counts`` instruction ceilings (budget.STABLE_MAX_COUNTS) and
ddp_bf16 pins the ``allowed_f32_dots=0`` low-precision contract.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import numpy as np

from pytorch_distributed_tpu.analysis.budget import (
    NO_COLLECTIVES,
    STABLE_MAX_COUNTS,
    CollectiveBudget,
    cost_budget_for,
    expected_budget,
    memory_budget_for,
    pin_max_counts,
)
from pytorch_distributed_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)


@dataclasses.dataclass(frozen=True)
class AuditCase:
    name: str
    description: str
    devices_needed: int
    # () -> (fn, args, budget, audit_kwargs)
    build: Callable[[], tuple]


def _tiny(
    n_experts: int = 0, dtype: str = "float32", **overrides
) -> ModelConfig:
    kw = dict(
        vocab_size=128, n_ctx=16, n_embd=64, n_layer=2, n_head=4,
        dtype=dtype, embd_pdrop=0.0, attn_pdrop=0.0, resid_pdrop=0.0,
    )
    if n_experts:
        kw.update(n_experts=n_experts, expert_capacity_factor=8.0)
    kw.update(overrides)
    return ModelConfig(**kw)


def _tcfg(micro: int = 16) -> TrainConfig:
    return TrainConfig(
        global_batch_size=16, micro_batch_size=micro, num_steps=1,
        learning_rate=1e-3,
    )


def _batch(rng_seed: int = 0, shape=(1, 16, 16)) -> dict:
    rng = np.random.default_rng(rng_seed)
    return {
        "inputs": rng.integers(0, 128, shape).astype(np.int32),
        "targets": rng.integers(0, 128, shape).astype(np.int32),
    }


def _build_baseline():
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.train.trainer import make_train_step
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny()
    model = get_model(cfg)
    tx = make_optimizer(_tcfg())
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    step = make_train_step(model, cfg, tx)
    args = (state, _batch(), jax.random.key(0))
    return step, args, NO_COLLECTIVES, {"compute_dtype": cfg.dtype}


def _build_train_guard():
    """The baseline step with the traced anomaly guard compiled in
    (train/guard.py): the guard's contract is that detection + the no-op
    select add ZERO collectives — pinned here the way the serving NaN
    sentinel is pinned on the decode programs."""
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.train.guard import (
        GuardConfig,
        init_guard_state,
    )
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.train.trainer import make_train_step
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny()
    model = get_model(cfg)
    tx = make_optimizer(_tcfg())
    state = init_train_state(
        model.init(domain_key(42, "init"), cfg), tx,
        guard=init_guard_state(),
    )
    step = make_train_step(
        model, cfg, tx,
        guard=GuardConfig(vocab_size=cfg.vocab_size),
    )
    args = (state, _batch(), jax.random.key(0))
    return step, args, NO_COLLECTIVES, {"compute_dtype": cfg.dtype}


def _build_explicit(
    mcfg: MeshConfig,
    n_experts: int = 0,
    budget_case: str | None = None,
    async_min_compute: int | None = None,
    audit_extra: dict | None = None,
    **model_overrides,
):
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.parallel import make_mesh, shard_train_state
    from pytorch_distributed_tpu.parallel.explicit import (
        make_explicit_train_step,
    )
    from pytorch_distributed_tpu.parallel.mesh import make_batch_put
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny(n_experts, **model_overrides)
    model = get_model(cfg)
    tx = make_optimizer(_tcfg())
    mesh = make_mesh(mcfg)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    state, _ = shard_train_state(state, mesh, mcfg)
    step = make_explicit_train_step(model, cfg, tx, mesh, mcfg, state)
    batch = make_batch_put(mesh, mcfg)(_batch())
    args = (state, batch, jax.random.key(0))
    budget = expected_budget(mcfg, cfg)
    if budget_case is not None:
        budget = pin_max_counts(budget, budget_case)
    if async_min_compute is not None:
        # Overlap contract: on async-collective backends (TPU/GPU) every
        # start/done pair must bracket compute; sync backends record an
        # info note (budget.check_async_overlap).
        budget = dataclasses.replace(
            budget, async_min_compute=async_min_compute
        )
    audit_kwargs = {"compute_dtype": cfg.dtype}
    if cfg.dtype == "bfloat16":
        # The bf16 contract: ZERO all-f32 matmuls. The f32-OUT dots the
        # histogram shows are bf16-in/f32-out (MXU accumulation + the
        # f32 logits head) — allowed by design, not counted as leaks.
        audit_kwargs["allowed_f32_dots"] = 0
    audit_kwargs.update(audit_extra or {})
    return step, args, budget, audit_kwargs


def _build_decode_engine(
    kind: str,
    mesh_cfg: MeshConfig | None = None,
    budget: CollectiveBudget | None = NO_COLLECTIVES,
    budget_case: str | None = None,
    async_min_compute: int | None = None,
):
    """A serving-engine decode program (serving/engine.py): the EXACT
    jitted prefill / decode_step / decode_run the engine dispatches, with
    the KV cache donated at its real argnum — audited with
    ``donation_strict`` because in-place cache reuse IS the serving
    contract (a rejected alias double-buffers the largest tensor in the
    server on every step)."""
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.serving.engine import (
        BucketSpec,
        DecodeEngine,
    )
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny()
    params = get_model(cfg).init(domain_key(42, "init"), cfg)
    engine = DecodeEngine(
        cfg, max_len=16, buckets=BucketSpec((8, 16)), mesh_cfg=mesh_cfg
    )
    fn = engine.program(kind, sampled=True)
    args = engine.example_args(kind, params, batch=1, sampled=True)
    if budget_case is not None:
        budget = pin_max_counts(budget, budget_case)
    if async_min_compute is not None:
        budget = dataclasses.replace(
            budget, async_min_compute=async_min_compute
        )
    return fn, args, budget, {
        "compute_dtype": cfg.dtype,
        "donate_argnums": (engine.CACHE_ARGNUM[kind],),
        "donation_strict": True,
    }


def _build_batched_engine(
    kind: str,
    mesh_cfg: MeshConfig | None = None,
    budget: CollectiveBudget | None = NO_COLLECTIVES,
    budget_case: str | None = None,
    weight_quant: str = "none",
    lora_rank: int | None = None,
    speculative_k: int = 0,
    audit_extra: dict | None = None,
):
    """A slot-batched serving program (serving/engine.BatchedDecodeEngine):
    the EXACT jitted prefill / decode_step the scheduler dispatches. All
    per-row state (pos, fold counters, sampling params, keys) is traced,
    so ONE executable covers every admission/retirement pattern — which is
    also why the pinned collective counts are invariant to how many rows
    are active: activity never reaches the program. Audited with
    ``donation_strict`` (a rejected alias would double-buffer the whole
    (slots, max_len) cache every token)."""
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.serving.engine import (
        BatchedDecodeEngine,
        BucketSpec,
    )
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny()
    params = get_model(cfg).init(domain_key(42, "init"), cfg)
    engine = BatchedDecodeEngine(
        cfg, slots=4, max_len=16, buckets=BucketSpec((8, 16)),
        mesh_cfg=mesh_cfg, weight_quant=weight_quant,
        adapters=_lora_registry(cfg, lora_rank),
        speculative_k=speculative_k,
    )
    fn = engine.program(kind)
    args = engine.example_args(kind, engine._place_params(params))
    if budget_case is not None:
        budget = pin_max_counts(budget, budget_case)
    return fn, args, budget, {
        "compute_dtype": cfg.dtype,
        "donate_argnums": (engine.CACHE_ARGNUM[kind],),
        "donation_strict": True,
        **(audit_extra or {}),
    }


def _lora_registry(cfg, rank: int | None):
    """A one-tenant AdapterRegistry for the LoRA audit cases (None ->
    no registry: the adapter-less program signatures). One registered
    tenant is enough — the traced operand shapes carry ``max_tenants +
    1`` slots either way, and the audit pins structure, not values."""
    if rank is None:
        return None
    from pytorch_distributed_tpu.serving.adapters import AdapterRegistry
    from pytorch_distributed_tpu.utils.prng import domain_key

    reg = AdapterRegistry(cfg, rank=rank, max_tenants=2)
    reg.register("audit-tenant", key=domain_key(7, "misc"))
    return reg


def _build_paged_engine(
    kind: str,
    budget: CollectiveBudget | None = NO_COLLECTIVES,
    mesh_cfg: MeshConfig | None = None,
    kv_quant: str = "none",
    weight_quant: str = "none",
    lora_rank: int | None = None,
    speculative_k: int = 0,
    role: str = "colocated",
    audit_extra: dict | None = None,
):
    """A paged slot-batched serving program
    (serving/engine.PagedBatchedDecodeEngine): the EXACT jitted chunked
    prefill / block-table decode step the scheduler dispatches. Block
    tables are traced int32 operands, so — like the dense batched cases
    — one executable covers every table content, and the audited
    contract is strict donation of the WHOLE page pool (a rejected
    alias would double-buffer the pool every token) plus NO_COLLECTIVES
    on the single-device programs.

    The kv handoff kinds ride the same builder: ``kv_import`` (the
    decode-worker scatter) donates the pool like every other paged
    program; ``kv_export`` (the prefill-worker gather) deliberately has
    NO donation — the source row must survive until the destination
    confirms (PR-6 fault model), so aliasing the pool into the gathered
    pages would be a correctness bug, not an optimisation."""
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.serving.engine import (
        PagedBatchedDecodeEngine,
    )
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny()
    params = get_model(cfg).init(domain_key(42, "init"), cfg)
    engine = PagedBatchedDecodeEngine(
        cfg, slots=4, max_len=16, page_size=8, pool_pages=8,
        prefill_chunk=8, mesh_cfg=mesh_cfg, kv_quant=kv_quant,
        weight_quant=weight_quant,
        adapters=_lora_registry(cfg, lora_rank),
        speculative_k=speculative_k, role=role,
    )
    fn = engine.program(kind)
    args = engine.example_args(kind, engine._place_params(params))
    ca = engine.CACHE_ARGNUM.get(kind)
    # kv_export is the one paged program with NO donation contract (the
    # source pool must outlive the gather — see class docstring), so
    # the audit must not apply the harness's default donate_argnums=(0,).
    donation = (
        {"expect_donation": False} if ca is None
        else {"donate_argnums": (ca,), "donation_strict": True}
    )
    return fn, args, budget, {
        "compute_dtype": cfg.dtype,
        **donation,
        **(audit_extra or {}),
    }


def _build_pipeline(schedule: str):
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel.pipeline import (
        make_pipeline_train_step,
        shard_pipeline_state,
    )
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny()
    tcfg = _tcfg(micro=4)
    model = get_model(cfg)
    tx = make_optimizer(tcfg)
    mcfg = MeshConfig(
        pipe=2, strategy="no_shard", pipe_schedule=schedule
    )
    mesh = make_mesh(mcfg)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    state, _ = shard_pipeline_state(state, mesh, mcfg)
    step = make_pipeline_train_step(
        model, cfg, tx, mesh, mcfg, state, tcfg, schedule=schedule
    )
    args = (state, _batch(shape=(4, 4, 16)), jax.random.key(0))
    return step, args, expected_budget(mcfg, cfg), {
        "compute_dtype": cfg.dtype
    }


def _build_pjit(mcfg: MeshConfig, n_experts: int = 0, budget="derive"):
    """The parallel/api.py (pjit/NamedSharding) twin of an explicit case.

    The pjit path's collectives are PLACED BY the SPMD partitioner, so
    for most strategies the emitted op set is a partitioner choice (e.g.
    ZeRO-2 resharding through all-to-all + all-gather on the CPU
    backend), not a written contract — those twins carry a relaxed
    budget (or none) and are equivalence-tested numerically instead; the
    donation/dtype/hazard/vma checks run at full strength either way
    (vma is vacuous here: no shard_map bodies — the partitioner owns
    replication, which is exactly why the explicit path needs vma-check
    and this one doesn't)."""
    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.parallel import make_mesh
    from pytorch_distributed_tpu.parallel.api import make_parallel_train_step
    from pytorch_distributed_tpu.train.optim import make_optimizer
    from pytorch_distributed_tpu.train.state import init_train_state
    from pytorch_distributed_tpu.utils.prng import domain_key

    cfg = _tiny(n_experts)
    model = get_model(cfg)
    tx = make_optimizer(_tcfg())
    mesh = make_mesh(mcfg)
    state = init_train_state(model.init(domain_key(42, "init"), cfg), tx)
    step, batch_put = make_parallel_train_step(
        model, cfg, tx, mesh, mcfg, state
    )
    args = (state, batch_put(_batch()), jax.random.key(0))
    if budget == "derive":
        budget = expected_budget(mcfg, cfg)
    return step, args, budget, {"compute_dtype": cfg.dtype}


def registered_cases() -> dict[str, AuditCase]:
    """name -> AuditCase for every audited (strategy x model) combo."""
    cases = [
        AuditCase(
            "baseline",
            "single-device jit train step (no mesh, no collectives)",
            1,
            _build_baseline,
        ),
        AuditCase(
            "train_guard",
            "guarded train step: traced anomaly guard adds no collectives",
            1,
            _build_train_guard,
        ),
        AuditCase(
            "ddp",
            "explicit DDP: data=8, no_shard (max_counts pinned)",
            8,
            lambda: _build_explicit(
                MeshConfig(data=8, strategy="no_shard"), budget_case="ddp"
            ),
        ),
        AuditCase(
            "ddp_bf16",
            "explicit DDP in bf16 compute: allowed_f32_dots=0 pinned",
            8,
            lambda: _build_explicit(
                MeshConfig(data=8, strategy="no_shard"), dtype="bfloat16",
                # Adjudicated for the --strict lane: the hot-path
                # bf16->f32 convert chains the dtype check flags are the
                # DELIBERATE mixed-precision accumulate
                # (parallel/explicit.py scan_body, accum_dtype="float32"
                # — bf16 grads upcast into the f32 accumulator each
                # micro-step). Removing them would accumulate in bf16
                # and lose low-order gradient bits across micro-batches;
                # the downgrade keeps the finding visible as info.
                audit_extra={
                    "dtype_allow": {
                        "convert-chain": (
                            "f32 master grad accumulation: bf16 "
                            "micro-grads are upcast into the f32 "
                            "accumulator by design (accum_dtype)"
                        ),
                    },
                },
            ),
        ),
        AuditCase(
            "fsdp",
            "explicit ZeRO-3: fsdp=8, full_shard (max_counts pinned)",
            8,
            lambda: _build_explicit(
                MeshConfig(fsdp=8, strategy="full_shard"),
                budget_case="fsdp",
            ),
        ),
        AuditCase(
            "zero2",
            "explicit ZeRO-2: fsdp=8, shard_grad_op",
            8,
            lambda: _build_explicit(
                MeshConfig(fsdp=8, strategy="shard_grad_op")
            ),
        ),
        AuditCase(
            "fsdp_prefetch",
            "explicit ZeRO-3 + latency-hiding window: fsdp=8, "
            "prefetch_buffers=1 (max_counts pinned, overlap contract)",
            8,
            lambda: _build_explicit(
                MeshConfig(
                    fsdp=8, strategy="full_shard", prefetch_buffers=1
                ),
                budget_case="fsdp_prefetch",
                async_min_compute=1,
            ),
        ),
        AuditCase(
            "zero2_bucketed",
            "explicit ZeRO-2 + bucketed reduce-scatter: fsdp=8, "
            "rs_buckets=2 (max_counts pinned)",
            8,
            lambda: _build_explicit(
                MeshConfig(
                    fsdp=8, strategy="shard_grad_op", rs_buckets=2
                ),
                budget_case="zero2_bucketed",
            ),
        ),
        AuditCase(
            "tp",
            "explicit tensor parallelism: tensor=4",
            4,
            lambda: _build_explicit(
                MeshConfig(tensor=4, strategy="no_shard")
            ),
        ),
        AuditCase(
            "ring",
            "ring-attention context parallelism: seq=4",
            4,
            lambda: _build_explicit(
                MeshConfig(seq=4, strategy="no_shard")
            ),
        ),
        AuditCase(
            "ulysses",
            "Ulysses sequence parallelism: seq=4, head/seq all-to-all",
            4,
            lambda: _build_explicit(
                MeshConfig(seq=4, strategy="no_shard"),
                seq_impl="ulysses",
            ),
        ),
        AuditCase(
            "ep",
            "expert parallelism: expert=4, 4-expert MoE",
            4,
            lambda: _build_explicit(
                MeshConfig(expert=4, strategy="no_shard"), n_experts=4
            ),
        ),
        AuditCase(
            "pipeline",
            "GPipe pipeline: pipe=2",
            2,
            _build_pipeline_gpipe,
        ),
        AuditCase(
            "pipeline_1f1b",
            "1F1B (PipeDream-flush) pipeline: pipe=2, hand-scheduled",
            2,
            _build_pipeline_1f1b,
        ),
        # Serving-engine decode programs (serving/engine.py): donation of
        # the KV cache is the contract under audit (strict aliasing), on
        # top of the collective budgets.
        AuditCase(
            "decode_prefill",
            "serving engine prefill (donated bucketed KV cache, traced "
            "sampling): single device, any collective is a bug",
            1,
            lambda: _build_decode_engine("prefill"),
        ),
        AuditCase(
            "decode_step",
            "serving engine single decode step (donated KV cache): "
            "single device, any collective is a bug",
            1,
            lambda: _build_decode_engine("decode_step"),
        ),
        AuditCase(
            "zero3_decode_prefetch",
            "serving engine ZeRO-3 decode_run: fsdp=8, full_shard, "
            "prefetch_buffers=1 windowed layer gathers (max_counts "
            "pinned, overlap contract)",
            8,
            lambda: _build_decode_engine(
                "decode_run",
                mesh_cfg=MeshConfig(
                    fsdp=8, strategy="full_shard", prefetch_buffers=1
                ),
                budget=CollectiveBudget(
                    required={"all-gather"},
                    note="ZeRO-3 decode must gather each layer's shards "
                         "(a window at a time); other resharding is the "
                         "partitioner's choice",
                ),
                budget_case="zero3_decode_prefetch",
                async_min_compute=1,
            ),
        ),
        # Slot-batched serving programs (continuous batching): per-row
        # positions/sampling are traced, so one executable serves every
        # admission/retirement pattern — collective counts CANNOT depend
        # on how many rows are active (pinned for the TP case).
        AuditCase(
            "decode_batched_prefill",
            "slot-batched prefill (gather rows -> forward -> scatter "
            "back, donated slot cache): single device, any collective "
            "is a bug",
            1,
            lambda: _build_batched_engine("prefill"),
        ),
        AuditCase(
            "decode_batched_step",
            "slot-batched decode step (per-row pos/sampling, donated "
            "slot cache): single device, any collective is a bug",
            1,
            lambda: _build_batched_engine("decode_step"),
        ),
        AuditCase(
            "decode_batched_step_tp",
            "slot-batched decode step over tensor=4 (head-sharded slot "
            "cache, Megatron psums; max_counts pinned — invariant to "
            "active-row count by construction)",
            4,
            lambda: _build_batched_engine(
                "decode_step",
                mesh_cfg=MeshConfig(tensor=4, strategy="no_shard"),
                budget=CollectiveBudget(
                    required={"all-reduce"},
                    forbidden={
                        "all-gather", "reduce-scatter", "all-to-all",
                        "collective-permute",
                    },
                    note="Megatron decode: psum at parallel-region "
                         "boundaries + replicated-logits reductions; "
                         "nothing else has any business here",
                ),
                budget_case="decode_batched_step_tp",
            ),
        ),
        # Paged slot-batched serving programs (block-pool KV cache):
        # chunked prefill + block-table decode; the donated buffer is the
        # whole page pool, and the tables are traced operands — one
        # executable per program regardless of allocation pattern.
        AuditCase(
            "decode_paged_prefill",
            "paged chunked prefill (per-row start/valid + block tables, "
            "donated page pool): single device, any collective is a bug",
            1,
            lambda: _build_paged_engine("prefill"),
        ),
        AuditCase(
            "decode_paged_step",
            "paged decode step (block-table page indirection, donated "
            "page pool): single device, any collective is a bug",
            1,
            lambda: _build_paged_engine("decode_step"),
        ),
        # Quantized serving programs: int8 KV pages (quantize-on-append,
        # dequant-on-read) + int8 weight-only projections. Same strict
        # donation + NO_COLLECTIVES contracts as the f32 paged cases,
        # PLUS the q8 cast budget: the program's int8 convert inventory
        # is pinned to its declared quantize/dequantize sites (2
        # appends; 2 KV reads + 4 gpt2 projection upcasts), so a silent
        # f32 round-trip on the quantized path FAILS the audit
        # (check_q8_casts; negative-tested in tests/test_quant.py).
        AuditCase(
            "decode_paged_prefill_q8",
            "int8 paged chunked prefill (quantize-on-append KV pages + "
            "weight-only int8 projections, donated int8 pool + scale "
            "pools): strict donation, no collectives, pinned q8 casts",
            1,
            lambda: _build_paged_engine(
                "prefill", kv_quant="int8", weight_quant="int8",
                audit_extra={
                    "q8_cast_budget": {"to_int8": 2, "from_int8": 6},
                },
            ),
        ),
        AuditCase(
            "decode_paged_step_q8",
            "int8 paged decode step (dequant-on-read block-table "
            "attention + weight-only int8 projections): strict "
            "donation, no collectives, pinned q8 casts",
            1,
            lambda: _build_paged_engine(
                "decode_step", kv_quant="int8", weight_quant="int8",
                audit_extra={
                    "q8_cast_budget": {"to_int8": 2, "from_int8": 6},
                },
            ),
        ),
        AuditCase(
            "decode_batched_step_tp_q8",
            "slot-batched decode step over tensor=4 with int8 weight-"
            "only projections: the per-channel scale is applied to the "
            "local partial BEFORE the psum, so the pinned Megatron "
            "all-reduce count (2) must survive quantization unchanged",
            4,
            lambda: _build_batched_engine(
                "decode_step",
                mesh_cfg=MeshConfig(tensor=4, strategy="no_shard"),
                weight_quant="int8",
                budget=CollectiveBudget(
                    required={"all-reduce"},
                    forbidden={
                        "all-gather", "reduce-scatter", "all-to-all",
                        "collective-permute",
                    },
                    note="int8 weights must not move the Megatron "
                         "collective structure: scales are linear "
                         "factors applied pre-psum",
                ),
                budget_case="decode_batched_step_tp",
                audit_extra={
                    "q8_cast_budget": {"to_int8": 0, "from_int8": 4},
                },
            ),
        ),
        # Batched speculative-decoding programs (serving/engine.py
        # speculative_k): the [B, k+1] verify forward with per-row
        # TRACED accept lengths. The contract under audit: acceptance
        # is data, not shape — drafts/accept lengths are operands and
        # outputs, so the programs keep the donated cache strictly
        # aliased, the single-device cases add no collectives, and the
        # TP case keeps the pinned Megatron all-reduce count (the k+1-
        # wide forward runs the SAME per-layer psums as the 1-wide
        # step). vma-check runs over the TP body like every shard_map
        # case — the accept-length chain derives from psum-replicated
        # logits, so it types invariant (the divergent-trip-count
        # hazard this program family could introduce is tested with a
        # deliberately-broken twin in tests/test_analysis.py).
        AuditCase(
            "decode_batched_spec_step",
            "slot-batched speculative verify step ([B, k+1] window, "
            "traced per-row accept lengths, donated slot cache): "
            "single device, any collective is a bug",
            1,
            lambda: _build_batched_engine(
                "decode_spec_step", speculative_k=3
            ),
        ),
        AuditCase(
            "decode_paged_spec_step",
            "paged speculative verify step (block-table k+1-token "
            "window, tail-page rollback, donated page pool): single "
            "device, any collective is a bug",
            1,
            lambda: _build_paged_engine(
                "decode_spec_step", speculative_k=3
            ),
        ),
        AuditCase(
            "decode_batched_step_tp_spec",
            "slot-batched speculative verify step over tensor=4: the "
            "k+1-wide forward must keep the pinned Megatron all-reduce "
            "count (2) — verification widens the token dim, never the "
            "collective structure, and the traced accept lengths "
            "derive from psum-replicated logits (vma-invariant)",
            4,
            lambda: _build_batched_engine(
                "decode_spec_step",
                mesh_cfg=MeshConfig(tensor=4, strategy="no_shard"),
                speculative_k=3,
                budget=CollectiveBudget(
                    required={"all-reduce"},
                    forbidden={
                        "all-gather", "reduce-scatter", "all-to-all",
                        "collective-permute",
                    },
                    note="speculative verification must not move the "
                         "Megatron collective structure: accept "
                         "lengths are elementwise functions of the "
                         "already-reduced logits",
                ),
                budget_case="decode_batched_step_tp",
            ),
        ),
        # Multi-tenant LoRA serving programs (serving/adapters.py): the
        # stacked per-tenant low-rank deltas ride the paged programs as
        # two extra TRACED operands (adapter tree + [B] tenant slots).
        # The contract under audit: adapters add einsums, never
        # collectives (per-row gathers are slot indexing, nothing
        # cross-row), and the donated page pool still strictly aliases
        # — N tenants cost zero extra compiles/caches by construction.
        AuditCase(
            "decode_paged_prefill_lora",
            "paged chunked prefill with per-row LoRA deltas (stacked "
            "adapter tree + tenant-slot vector as traced operands, "
            "donated page pool): single device, any collective is a bug",
            1,
            lambda: _build_paged_engine("prefill", lora_rank=4),
        ),
        AuditCase(
            "decode_paged_step_lora",
            "paged decode step with per-row LoRA deltas: strict "
            "donation of the pool, no collectives — tenant isolation "
            "is a gather, not a communication",
            1,
            lambda: _build_paged_engine("decode_step", lora_rank=4),
        ),
        AuditCase(
            "decode_batched_step_tp_lora",
            "slot-batched decode step over tensor=4 with per-row LoRA "
            "deltas: column-parallel targets shard the B factor, row-"
            "parallel targets join the base partial BEFORE the psum "
            "(linearity shares the reduction), so the pinned Megatron "
            "all-reduce count (2) must survive adapters unchanged",
            4,
            lambda: _build_batched_engine(
                "decode_step",
                mesh_cfg=MeshConfig(tensor=4, strategy="no_shard"),
                lora_rank=4,
                budget=CollectiveBudget(
                    required={"all-reduce"},
                    forbidden={
                        "all-gather", "reduce-scatter", "all-to-all",
                        "collective-permute",
                    },
                    note="adapters must not move the Megatron "
                         "collective structure: the delta is a per-row "
                         "linear term summed into the existing partial",
                ),
                budget_case="decode_batched_step_tp",
            ),
        ),
        # Disaggregated-serving kv handoff programs (serving/engine.py
        # export_handoff/import_handoff): the prefill-worker gather and
        # the decode-worker scatter that ship a finished row's pages +
        # block table between replicas. Contracts under audit: the
        # bodies are pure page movement — NO collectives even under TP
        # (each shard gathers/scatters ITS OWN head slice; resharding
        # in a handoff would be a silent wire-cost regression) — the
        # import donates the destination pool like every paged program,
        # and the export deliberately does NOT donate (the source row
        # must survive until the destination confirms; PR-6 fault
        # model).
        AuditCase(
            "decode_paged_kv_export",
            "kv handoff export (prefill worker gathers one parked "
            "row's pages off the pool at a traced block table): NO "
            "donation by design — the source pool outlives the wire "
            "copy until complete_handoff — and any collective is a bug",
            1,
            lambda: _build_paged_engine("kv_export", role="prefill"),
        ),
        AuditCase(
            "decode_paged_kv_import",
            "kv handoff import (decode worker scatters shipped pages "
            "into its pool at freshly allocated page ids): strict "
            "donation of the destination pool, any collective is a bug",
            1,
            lambda: _build_paged_engine("kv_import", role="decode"),
        ),
        AuditCase(
            "decode_paged_kv_import_q8",
            "int8 kv handoff import (int8 pages + per-row scale leaves "
            "scatter as-is): strict donation, no collectives, and a "
            "ZERO q8 cast budget — a handoff must never round-trip "
            "quantized pages through f32",
            1,
            lambda: _build_paged_engine(
                "kv_import", kv_quant="int8", role="decode",
                audit_extra={
                    "q8_cast_budget": {"to_int8": 0, "from_int8": 0},
                },
            ),
        ),
        AuditCase(
            "decode_paged_kv_import_tp",
            "kv handoff import over tensor=2 (head-sharded pool): each "
            "shard scatters its OWN head slice of the shipped pages — "
            "NO collectives pinned, because resharding inside a "
            "handoff would silently multiply the wire cost",
            2,
            lambda: _build_paged_engine(
                "kv_import",
                mesh_cfg=MeshConfig(tensor=2, strategy="no_shard"),
                role="decode",
            ),
        ),
        # pjit twins of the explicit cases (parallel/api.py). Budgets per
        # _build_pjit's docstring: derived where the partitioner's op set
        # is the written contract, relaxed/none where it reshards freely.
        AuditCase(
            "ddp_pjit",
            "pjit twin of ddp: partitioner-placed gradient all-reduce",
            8,
            lambda: _build_pjit(MeshConfig(data=8, strategy="no_shard")),
        ),
        AuditCase(
            "fsdp_pjit",
            "pjit twin of fsdp (ZeRO-3): param all-gather pinned",
            8,
            lambda: _build_pjit(
                MeshConfig(fsdp=8, strategy="full_shard"),
                budget=CollectiveBudget(
                    required={"all-gather"},
                    note="ZeRO-3 must gather params; the partitioner "
                         "reshards grads via its own op choice "
                         "(all-to-all/all-reduce on the CPU backend)",
                ),
            ),
        ),
        AuditCase(
            "zero2_pjit",
            "pjit twin of zero2: grad reduction pinned",
            8,
            lambda: _build_pjit(
                MeshConfig(fsdp=8, strategy="shard_grad_op"),
                budget=CollectiveBudget(
                    required={"all-reduce"},
                    note="ZeRO-2 under the partitioner: sharded-grad "
                         "resharding is its op choice; only the "
                         "reduction itself is pinned",
                ),
            ),
        ),
        AuditCase(
            "tp_pjit",
            "pjit twin of tp: Megatron psums placed by the partitioner",
            4,
            lambda: _build_pjit(MeshConfig(tensor=4, strategy="no_shard")),
        ),
        AuditCase(
            "ring_pjit",
            "pjit twin of ring: partitioner-chosen attention resharding "
            "(no op contract; audited for donation/dtype/hazards)",
            4,
            lambda: _build_pjit(
                MeshConfig(seq=4, strategy="no_shard"), budget=None
            ),
        ),
        AuditCase(
            "ep_pjit",
            "pjit twin of ep: expert dispatch all-to-all pinned",
            4,
            lambda: _build_pjit(
                MeshConfig(expert=4, strategy="no_shard"),
                n_experts=4,
                budget=CollectiveBudget(
                    required={"all-to-all"},
                    note="expert dispatch; other resharding is the "
                         "partitioner's choice",
                ),
            ),
        ),
    ]
    return {
        c.name: dataclasses.replace(
            c, build=_with_pinned_budgets(c.name, c.build)
        )
        for c in cases
    }


def _with_pinned_budgets(name: str, build: Callable[[], tuple]):
    """Attach the case's pinned MemoryBudget AND CostBudget at build time.

    Every registered program carries its STABLE_MEMORY_BUDGETS and
    STABLE_COST_BUDGETS pins the way the collective cases carry
    STABLE_MAX_COUNTS — and both ``*_budget_for`` lookups raise on a
    missing pin, so registering a new case without measuring its bytes
    and its FLOPs/traffic fails the audit instead of shipping an
    unpinned program. A case can still override by putting its own
    ``memory_budget``/``cost_budget`` in audit_kwargs (none do today)."""

    def wrapped():
        fn, args, budget, audit_kwargs = build()
        if "memory_budget" not in audit_kwargs:
            audit_kwargs["memory_budget"] = memory_budget_for(name)
        if "cost_budget" not in audit_kwargs:
            audit_kwargs["cost_budget"] = cost_budget_for(name)
        return fn, args, budget, audit_kwargs

    return wrapped


# Engine program kinds -> the registry case(s) auditing that compiled
# program, keyed by engine class name. The coverage gate
# (tests/test_memory_analysis.py) walks each engine's CACHE_ARGNUM —
# the authoritative list of program kinds an engine can dispatch — and
# asserts every kind appears here AND every named case is registered,
# so a new engine program cannot ship audit-unpinned.
ENGINE_PROGRAM_CASES: dict[str, dict[str, tuple[str, ...]]] = {
    "DecodeEngine": {
        "prefill": ("decode_prefill",),
        "decode_step": ("decode_step",),
        "decode_run": ("zero3_decode_prefetch",),
    },
    "BatchedDecodeEngine": {
        "prefill": ("decode_batched_prefill",),
        "decode_step": (
            "decode_batched_step",
            "decode_batched_step_tp",
            "decode_batched_step_tp_q8",
            "decode_batched_step_tp_lora",
        ),
        "decode_spec_step": (
            "decode_batched_spec_step",
            "decode_batched_step_tp_spec",
        ),
    },
    "PagedBatchedDecodeEngine": {
        "prefill": (
            "decode_paged_prefill",
            "decode_paged_prefill_q8",
            "decode_paged_prefill_lora",
        ),
        "decode_step": (
            "decode_paged_step",
            "decode_paged_step_q8",
            "decode_paged_step_lora",
        ),
        "decode_spec_step": ("decode_paged_spec_step",),
        # kv_export has no CACHE_ARGNUM entry (no donation by design),
        # so the coverage gate doesn't require it here — its case
        # (decode_paged_kv_export) registers standalone above.
        "kv_import": (
            "decode_paged_kv_import",
            "decode_paged_kv_import_q8",
            "decode_paged_kv_import_tp",
        ),
    },
}


def _build_pipeline_gpipe():
    return _build_pipeline("gpipe")


def _build_pipeline_1f1b():
    return _build_pipeline("1f1b")
