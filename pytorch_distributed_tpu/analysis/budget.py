"""Collective and memory budgets: what a program is ALLOWED to emit/hold.

Generalises the hard-coded per-strategy assertions of
tests/test_hlo_collectives.py into a reusable contract object:

- ``required``: base opcodes that MUST appear (the collectives the
  strategy's design promises — FSDP gathers+scatters, DDP all-reduces,
  ring permutes, EP all-to-alls);
- ``forbidden``: opcodes that must NOT appear (a sharding edit that sneaks
  an all-gather into a DDP step is exactly the silent regression this
  subsystem exists to catch);
- ``max_counts``: optional per-opcode instruction-count ceilings for
  programs whose collective count is part of the perf contract (e.g. ONE
  gradient all-reduce at the accumulation boundary).

``expected_budget`` derives the contract for a MeshConfig the same way the
strategies themselves are written (parallel/explicit.py, parallel/pipeline.py).

``MemoryBudget`` is the peer contract for bytes (analysis/memory.py's
static peak-HBM estimate): pinned ``max_live_bytes`` ceilings per
registered program, a hard cap on the bytes a donated input may fail to
alias (``check_memory`` names the parameter when XLA rejects a donation),
and an optional ceiling on the donated buffer itself (the int8-pool
contract — an upcast to f32 triples the pool and must fail the audit).
"""

from __future__ import annotations

import dataclasses

from pytorch_distributed_tpu.analysis.hlo import (
    HLO_COLLECTIVES,
    AsyncCollective,
)
from pytorch_distributed_tpu.analysis.report import Finding
from pytorch_distributed_tpu.config import MeshConfig, ModelConfig


@dataclasses.dataclass(frozen=True)
class CollectiveBudget:
    required: frozenset = frozenset()
    forbidden: frozenset = frozenset()
    max_counts: dict = dataclasses.field(default_factory=dict)
    note: str = ""
    # Overlap contract: when not None, every async collective
    # start/done pair the compiled module schedules must have at least
    # this many compute instructions between start and done
    # (analysis/hlo.async_collective_pairs) — the machine-checkable form
    # of "the transfer is hidden under compute, not just async-shaped".
    # Backends that emit synchronous collectives (XLA:CPU) produce no
    # pairs; the check then reports an info note instead of passing
    # silently (check_async_overlap).
    async_min_compute: int | None = None

    def __post_init__(self) -> None:
        object.__setattr__(self, "required", frozenset(self.required))
        object.__setattr__(self, "forbidden", frozenset(self.forbidden))
        for op in self.required | self.forbidden | set(self.max_counts):
            if op not in HLO_COLLECTIVES:
                raise ValueError(
                    f"unknown collective opcode {op!r}; known: "
                    f"{HLO_COLLECTIVES}"
                )
        overlap = self.required & self.forbidden
        if overlap:
            raise ValueError(
                f"opcodes both required and forbidden: {sorted(overlap)}"
            )


NO_COLLECTIVES = CollectiveBudget(
    forbidden=frozenset(HLO_COLLECTIVES),
    note="single-device program: any collective is a bug",
)


# Instruction-count ceilings for the registered cases whose collective
# count IS the perf contract, measured once on the tiny registry models
# (XLA:CPU, jax 0.4.37) and pinned. The numbers are per-HLO-module
# instruction counts, not logical collectives: XLA emits one all-reduce
# per psum operand, so DDP's "ONE gradient all-reduce" (a single variadic
# psum over the 15-leaf grad tree) plus the loss/metric reductions lands
# at 17 instructions; ZeRO-3's just-in-time gathers are per-leaf, per
# direction (forward gather + remat re-gather in backward), and its
# reduce-scatters are the gathers' AD transposes. A future edit that
# re-gathers params twice, loses the accumulate-locally/reduce-once
# structure, or sneaks a second grad reduction blows the ceiling.
#
# The latency-hiding schedule cases (PR 3):
# - fsdp_prefetch (prefetch_buffers=1 on the 2-layer registry model =
#   one 2-layer window): the window body textually contains W=2 copies
#   of each per-leaf gather/scatter, so the STATIC instruction count
#   roughly doubles while the DYNAMIC per-step collective count is
#   unchanged (W x per-body collectives x L/W trip count). The ceiling
#   pins that static shape — growth past it means the window gained a
#   third gather of the same leaf or lost the re-gather structure.
# - zero2_bucketed (rs_buckets=2): the per-leaf boundary psum_scatters
#   coalesce into exactly rs_buckets bucket collectives — THE schedule
#   contract; a 3rd reduce-scatter means bucketing silently broke.
# - zero3_decode_prefetch (the serving engine's ZeRO-3 decode_run,
#   prefetch_buffers=1 on the 2-layer registry model = one 2-layer
#   window): the partitioner's per-leaf layer gathers appear W=2 times
#   in the window body plus the up-front non-block gathers; growth past
#   the ceiling means a layer's shards started gathering twice per use
#   (or the embedding/head gathers moved inside the token loop). The
#   all-reduces are the partitioner's logit/softmax reductions.
STABLE_MAX_COUNTS: dict[str, dict[str, int]] = {
    "ddp": {"all-reduce": 17},
    "fsdp": {"all-gather": 27, "reduce-scatter": 16, "all-reduce": 2},
    "fsdp_prefetch": {
        "all-gather": 51, "reduce-scatter": 28, "all-reduce": 2,
    },
    "zero2_bucketed": {"reduce-scatter": 2, "all-reduce": 18},
    "zero3_decode_prefetch": {"all-gather": 28, "all-reduce": 11},
    # Slot-batched TP decode step (serving/engine.BatchedDecodeEngine):
    # exactly the scanned block body's two Megatron psums (attention
    # c_proj + MLP c_proj), emitted ONCE each thanks to the layer scan —
    # and, because every per-row quantity (pos, fold, sampling params,
    # active pattern) is a traced operand, this count is INVARIANT to how
    # many rows are active: admissions/retirements never touch the
    # program. Growth means per-row handling leaked a collective (e.g.
    # sampling started psumming per row) or the scan unrolled.
    "decode_batched_step_tp": {"all-reduce": 2},
}


def pin_max_counts(budget: CollectiveBudget, case: str) -> CollectiveBudget:
    """``budget`` with the STABLE_MAX_COUNTS ceilings for ``case``."""
    counts = STABLE_MAX_COUNTS[case]
    return dataclasses.replace(
        budget,
        max_counts={**budget.max_counts, **counts},
        note=f"{budget.note}; max_counts pinned ({case})".strip("; "),
    )


@dataclasses.dataclass(frozen=True)
class MemoryBudget:
    """Byte ceilings for one compiled program's static memory estimate.

    ``max_live_bytes``: ceiling on the liveness-scan peak
    (memory.MemoryEstimate.peak_live_bytes). Pinned per registered case in
    ``STABLE_MEMORY_BUDGETS`` the way STABLE_MAX_COUNTS pins collective
    counts: measured once on the tiny registry models and frozen, so a
    regression that doubles a live buffer blows the ceiling.
    ``max_unaliased_donated_bytes``: how many bytes of DONATED input XLA
    may fail to alias before the audit errors. 0 for the serving engines
    (in-place cache reuse IS the contract); a measured allowance for
    training cases that tolerate the odd reshaped optimizer slot.
    ``max_donated_bytes``: optional ceiling on the donated argument's own
    size — the quantized-pool contract (an int8 page pool silently upcast
    to f32 is ~4x these bytes and must fail loudly, independent of what
    the rest of the program does).
    ``max_loop_body_peak_bytes``: optional ceiling on the largest while-body
    liveness peak — the steady-state-HBM contract for decode loops, where
    the token loop's per-iteration footprint (not the one-shot entry
    setup) is what an accelerator actually holds for the life of a
    generation. Pinned for the serving decode cases.
    """

    max_live_bytes: int | None = None
    max_unaliased_donated_bytes: int = 0
    max_donated_bytes: int | None = None
    max_loop_body_peak_bytes: int | None = None
    note: str = ""


def check_memory(
    estimate,
    budget: MemoryBudget | None,
    *,
    donated_params: frozenset = frozenset(),
) -> tuple[list[Finding], dict]:
    """Diff a program's static memory estimate against its byte budget.

    ``estimate``: analysis/memory.estimate_memory over the compiled
    module text. ``donated_params``: the entry-parameter numbers the call
    site donated (audit.donated_param_numbers) — every one of them should
    appear in the accepted input_output_alias map; one that does not is
    double-buffered at runtime, and the finding NAMES it (parameter
    number, HLO name, shape, bytes) so the shape/dtype change that broke
    the alias is findable. Returns (findings, stats); a None budget
    records stats without judging them.
    """
    unaliased = sorted(donated_params - estimate.aliased_params)
    unaliased_bytes = estimate.param_bytes(unaliased)
    donated_bytes = estimate.param_bytes(donated_params)
    loop_peaks = {
        name: est.peak_live_bytes
        for name, est in estimate.loop_bodies().items()
    }
    stats = {
        "peak_live_bytes": estimate.peak_live_bytes,
        "raw_peak_bytes": estimate.raw_peak_bytes,
        "alias_saved_bytes": estimate.alias_saved_bytes,
        "parameter_bytes": estimate.parameter_bytes,
        "donated_bytes": donated_bytes,
        "unaliased_donated_bytes": unaliased_bytes,
        "unaliased_donated_params": unaliased[:16],
        "loop_body_peak_bytes": (
            max(loop_peaks.values()) if loop_peaks else 0
        ),
    }
    findings: list[Finding] = []
    if budget is None:
        return findings, stats
    stats["budget"] = {
        "max_live_bytes": budget.max_live_bytes,
        "max_unaliased_donated_bytes": budget.max_unaliased_donated_bytes,
        "max_donated_bytes": budget.max_donated_bytes,
        "max_loop_body_peak_bytes": budget.max_loop_body_peak_bytes,
        "note": budget.note,
    }

    if (
        budget.max_live_bytes is not None
        and estimate.peak_live_bytes > budget.max_live_bytes
    ):
        findings.append(
            Finding(
                checker="memory",
                code="memory-budget-exceeded",
                severity="error",
                message=(
                    f"static peak {estimate.peak_live_bytes:,} bytes > "
                    f"pinned ceiling {budget.max_live_bytes:,} — a live "
                    "buffer grew (lost alias, upcast, or a new "
                    "materialisation); re-pin only if the growth is a "
                    "deliberate contract change"
                ),
                detail={
                    "peak_live_bytes": estimate.peak_live_bytes,
                    "max_live_bytes": budget.max_live_bytes,
                },
            )
        )
    if unaliased_bytes > budget.max_unaliased_donated_bytes:
        for pn in unaliased:
            p = estimate.parameters.get(pn)
            findings.append(
                Finding(
                    checker="memory",
                    code="donated-param-not-aliased",
                    severity="error",
                    message=(
                        f"donated parameter {pn}"
                        + (
                            f" (%{p.name}: {p.shape}, {p.bytes:,} bytes)"
                            if p is not None else ""
                        )
                        + " has NO accepted output alias — XLA rejected "
                        "the donation, so those bytes are double-buffered "
                        "every call; find the shape/dtype change between "
                        "this input and the output meant to reuse it"
                    ),
                    detail={
                        "param_number": pn,
                        "param_name": p.name if p else None,
                        "shape": p.shape if p else None,
                        "bytes": p.bytes if p else None,
                        "allowance": budget.max_unaliased_donated_bytes,
                    },
                )
            )
    elif unaliased:
        findings.append(
            Finding(
                checker="memory",
                code="unaliased-donated-within-allowance",
                severity="info",
                message=(
                    f"{len(unaliased)} donated parameter(s) "
                    f"({unaliased_bytes:,} bytes) not aliased, within the "
                    f"budget's {budget.max_unaliased_donated_bytes:,}-byte "
                    "allowance"
                ),
                detail={"params": unaliased[:16],
                        "bytes": unaliased_bytes},
            )
        )
    if (
        budget.max_loop_body_peak_bytes is not None
        and stats["loop_body_peak_bytes"] > budget.max_loop_body_peak_bytes
    ):
        findings.append(
            Finding(
                checker="memory",
                code="loop-body-peak-exceeded",
                severity="error",
                message=(
                    f"largest while-body liveness peak "
                    f"{stats['loop_body_peak_bytes']:,} bytes > pinned "
                    f"ceiling {budget.max_loop_body_peak_bytes:,} — the "
                    "steady-state decode-loop footprint grew (a "
                    "per-iteration buffer stopped aliasing or a setup "
                    "tensor moved inside the token loop)"
                ),
                detail={
                    "loop_body_peak_bytes": stats["loop_body_peak_bytes"],
                    "max_loop_body_peak_bytes":
                        budget.max_loop_body_peak_bytes,
                    "loop_bodies": loop_peaks,
                },
            )
        )
    if (
        budget.max_donated_bytes is not None
        and donated_bytes > budget.max_donated_bytes
    ):
        findings.append(
            Finding(
                checker="memory",
                code="donated-bytes-exceeded",
                severity="error",
                message=(
                    f"donated argument is {donated_bytes:,} bytes > "
                    f"pinned ceiling {budget.max_donated_bytes:,} — the "
                    "donated buffer itself grew (e.g. an int8 pool "
                    "silently upcast to full precision)"
                ),
                detail={
                    "donated_bytes": donated_bytes,
                    "max_donated_bytes": budget.max_donated_bytes,
                },
            )
        )
    return findings, stats


# Pinned static-memory ceilings per registered audit case, the bytes
# counterpart of STABLE_MAX_COUNTS: max_live_bytes is the measured
# liveness-scan peak of the compiled program on the tiny registry
# models (8 virtual CPU devices), frozen exactly — any growth is a
# regression until adjudicated and re-pinned (shrinkage passes: these
# are ceilings). max_donated_bytes pins the donated cache/pool argument
# itself for the serving cases, where its size IS the claim: the dense
# slot cache and the paged pool are both 65_536 B at the registry's
# equal-slots config (pool_pages*page_size == slots*max_len — paged
# wins by allocating FEWER pages, not smaller ones), and the int8 pool
# is 20_480 B = 0.3125x f32, exactly (head_dim+4)/(4*head_dim) at
# head_dim 16 (per-token f32 scales amortized over the head); an
# upcast to f32 lands at 65_536+ and fails donated-bytes-exceeded.
# max_unaliased_donated_bytes stays at its 0 default everywhere —
# measured: XLA accepts EVERY donated alias in every program at HEAD.
# Re-pin procedure: docs/ANALYSIS.md §6.
STABLE_MEMORY_BUDGETS: dict[str, MemoryBudget] = {
    "baseline": MemoryBudget(max_live_bytes=4_784_172),
    "train_guard": MemoryBudget(max_live_bytes=4_783_176),
    "ddp": MemoryBudget(max_live_bytes=2_458_408),
    "ddp_bf16": MemoryBudget(
        max_live_bytes=2_758_952,
        note="above f32 ddp: the f32 grad accumulator + bf16 activation "
             "copies coexist at the backward peak on this tiny model",
    ),
    "fsdp": MemoryBudget(max_live_bytes=709_868),
    "zero2": MemoryBudget(max_live_bytes=2_090_536),
    "fsdp_prefetch": MemoryBudget(
        max_live_bytes=733_152,
        note="the +1-layer prefetch window costs ~23 KiB over plain "
             "fsdp — the bounded-extra-live-bytes overlap claim",
    ),
    "zero2_bucketed": MemoryBudget(max_live_bytes=2_090_280),
    "tp": MemoryBudget(max_live_bytes=1_977_900),
    "ring": MemoryBudget(max_live_bytes=3_139_616),
    "ulysses": MemoryBudget(max_live_bytes=2_755_628),
    "ep": MemoryBudget(max_live_bytes=5_391_952),
    "pipeline": MemoryBudget(max_live_bytes=3_966_421),
    "pipeline_1f1b": MemoryBudget(
        max_live_bytes=1_540_180,
        note="~0.39x GPipe peak: 1F1B's bounded in-flight microbatches, "
             "reproduced from static bytes alone",
    ),
    "decode_prefill": MemoryBudget(
        max_live_bytes=554_156, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=290_956,
    ),
    "decode_step": MemoryBudget(
        max_live_bytes=486_972, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=223_776,
    ),
    "zero3_decode_prefetch": MemoryBudget(
        max_live_bytes=299_766, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=242_286,
    ),
    "decode_batched_prefill": MemoryBudget(
        max_live_bytes=619_697, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=290_956,
    ),
    "decode_batched_step": MemoryBudget(
        max_live_bytes=672_000, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=408_724,
    ),
    "decode_batched_step_tp": MemoryBudget(
        max_live_bytes=197_760, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=106_516,
    ),
    "decode_paged_prefill": MemoryBudget(
        max_live_bytes=681_213, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=417_956,
    ),
    "decode_paged_step": MemoryBudget(
        max_live_bytes=672_000, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=408_724,
    ),
    "decode_paged_prefill_q8": MemoryBudget(
        max_live_bytes=275_461, max_donated_bytes=20_480,
        max_loop_body_peak_bytes=196_668,
        note="int8 pool + per-token scales: 0.3125x the f32 pool at "
             "head_dim 16; an f32 upcast fails donated-bytes-exceeded",
    ),
    "decode_paged_step_q8": MemoryBudget(
        max_live_bytes=267_656, max_donated_bytes=20_480,
        max_loop_body_peak_bytes=188_828,
        note="int8 pool + per-token scales: 0.3125x the f32 pool at "
             "head_dim 16; an f32 upcast fails donated-bytes-exceeded",
    ),
    "decode_batched_step_tp_q8": MemoryBudget(
        max_live_bytes=125_952, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=69_524,
    ),
    "decode_batched_spec_step": MemoryBudget(
        max_live_bytes=699_984, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=436_628,
    ),
    "decode_paged_spec_step": MemoryBudget(
        max_live_bytes=700_016, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=436_564,
    ),
    "decode_batched_step_tp_spec": MemoryBudget(
        max_live_bytes=211_920, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=120_596,
    ),
    "decode_paged_prefill_lora": MemoryBudget(
        max_live_bytes=705_794, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=436_524,
    ),
    "decode_paged_step_lora": MemoryBudget(
        max_live_bytes=696_612, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=427_328,
    ),
    "decode_batched_step_tp_lora": MemoryBudget(
        max_live_bytes=213_156, max_donated_bytes=16_384,
        max_loop_body_peak_bytes=115_736,
    ),
    "decode_paged_kv_export": MemoryBudget(
        max_live_bytes=73_736,
        max_loop_body_peak_bytes=0,
        note="pool + gathered pages both live: export does NOT donate "
             "(the source row must survive until complete_handoff); "
             "no while loop, so the body peak is zero by construction",
    ),
    "decode_paged_kv_import": MemoryBudget(
        max_live_bytes=114_716, max_donated_bytes=65_536,
        max_loop_body_peak_bytes=73_752,
    ),
    "decode_paged_kv_import_q8": MemoryBudget(
        max_live_bytes=33_824, max_donated_bytes=20_480,
        max_loop_body_peak_bytes=18_456,
        note="int8 pages + per-token scale leaves scatter as-is: "
             "0.3125x the f32 import's pool bytes",
    ),
    "decode_paged_kv_import_tp": MemoryBudget(
        max_live_bytes=57_372, max_donated_bytes=32_768,
        max_loop_body_peak_bytes=36_888,
        note="per-shard bytes: each tensor=2 shard scatters its own "
             "head slice, half the single-device pool",
    ),
    "ddp_pjit": MemoryBudget(max_live_bytes=2_458_808),
    "fsdp_pjit": MemoryBudget(max_live_bytes=1_094_776),
    "zero2_pjit": MemoryBudget(max_live_bytes=1_558_768),
    "tp_pjit": MemoryBudget(max_live_bytes=1_977_900),
    "ring_pjit": MemoryBudget(max_live_bytes=2_737_788),
    "ep_pjit": MemoryBudget(max_live_bytes=6_461_028),
}


def memory_budget_for(case: str) -> MemoryBudget:
    """The pinned STABLE_MEMORY_BUDGETS entry for ``case``.

    KeyError (with the fix spelled out) when the case has no pin: every
    registered program must carry a memory budget, so a new engine
    program cannot ship audit-unpinned.
    """
    try:
        return STABLE_MEMORY_BUDGETS[case]
    except KeyError:
        raise KeyError(
            f"no pinned memory budget for registered case {case!r} — "
            "measure it (scripts/audit.py --case "
            f"{case} --only memory --json r.json, read "
            "summary.memory) and add a STABLE_MEMORY_BUDGETS entry "
            "(docs/ANALYSIS.md §6 documents the re-pin procedure)"
        ) from None


@dataclasses.dataclass(frozen=True)
class CostBudget:
    """Pinned per-step throughput-resource ceilings for one program.

    The three quantities analysis/cost.py derives statically from the
    scheduled HLO — FLOPs executed, HBM bytes moved, collective wire
    bytes — frozen per registered case in ``STABLE_COST_BUDGETS`` the
    way STABLE_MEMORY_BUDGETS freezes peak-live bytes. Exceeding any
    ceiling is a perf regression (a doubled matmul, an upcast page
    pool, an un-coalesced collective) until adjudicated and re-pinned;
    shrinkage always passes. ``allow_lower_bound`` acknowledges a
    program whose cost is a loud lower bound (an unknown-trip-count
    while); pinned programs default to refusing that, so a scheduling
    change that hides a loop's trip count cannot quietly deflate its
    pinned numbers.
    """

    max_flops: int | None = None
    max_hbm_bytes: int | None = None
    max_wire_bytes: int | None = None
    allow_lower_bound: bool = False
    note: str = ""


def check_cost(cost, budget: CostBudget | None) -> tuple[list[Finding], dict]:
    """Diff a program's static cost estimate against its pinned budget.

    ``cost``: analysis/cost.estimate_cost over the compiled module text.
    Returns (findings, stats); a None budget records stats without
    judging them (scripts/audit.py still prints them).
    """
    stats = {
        "flops": cost.flops,
        "hbm_bytes": cost.hbm_bytes,
        "wire_bytes": cost.wire_bytes,
        "wire_by_collective": dict(cost.wire_by_collective),
        "arithmetic_intensity": round(cost.arithmetic_intensity, 4),
        "lower_bound": cost.lower_bound,
        "unknown_trip_whiles": list(cost.unknown_trip_whiles),
        "num_partitions": cost.num_partitions,
    }
    findings: list[Finding] = []
    if budget is None:
        return findings, stats
    stats["budget"] = {
        "max_flops": budget.max_flops,
        "max_hbm_bytes": budget.max_hbm_bytes,
        "max_wire_bytes": budget.max_wire_bytes,
        "note": budget.note,
    }
    if cost.lower_bound and not budget.allow_lower_bound:
        findings.append(
            Finding(
                checker="cost",
                code="cost-lower-bound",
                severity="error",
                message=(
                    "cost estimate is only a LOWER BOUND: while loop(s) "
                    f"{list(cost.unknown_trip_whiles)} carry no static "
                    "trip count, so their bodies were counted once — the "
                    "pinned ceilings cannot certify this program; derive "
                    "the trip count or set allow_lower_bound with "
                    "reasoning"
                ),
                detail={"whiles": list(cost.unknown_trip_whiles)},
            )
        )
    for label, got, cap in (
        ("flops", cost.flops, budget.max_flops),
        ("hbm_bytes", cost.hbm_bytes, budget.max_hbm_bytes),
        ("wire_bytes", cost.wire_bytes, budget.max_wire_bytes),
    ):
        if cap is not None and got > cap:
            findings.append(
                Finding(
                    checker="cost",
                    code=f"cost-{label.replace('_', '-')}-exceeded",
                    severity="error",
                    message=(
                        f"static {label} {got:,} > pinned ceiling "
                        f"{cap:,} — the per-step {label} grew (doubled "
                        "math, upcast traffic, or an extra collective); "
                        "re-pin only if the growth is a deliberate "
                        "contract change (docs/ANALYSIS.md §7)"
                    ),
                    detail={label: got, f"max_{label}": cap},
                )
            )
    return findings, stats


# Pinned static-cost ceilings per registered audit case — the
# throughput counterpart of STABLE_MEMORY_BUDGETS. Each triple is the
# measured per-chip FLOPs / HBM-bytes-moved / collective-wire-bytes of
# the compiled program on the tiny registry models (8 virtual CPU
# devices, XLA:CPU schedule, jax 0.4.37), frozen exactly: growth in any
# number is a perf regression (doubled math, upcast traffic, extra or
# fatter collectives) until adjudicated and re-pinned; shrinkage always
# passes. The relationships BETWEEN pins are themselves claims the test
# suite re-derives from cost alone (tests/test_cost_analysis.py):
# - the q8 decode steps move FEWER HBM bytes than their f32 twins
#   (1_935_015 < 3_411_430: int8 pages are real traffic, not just a
#   smaller allocation);
# - zero2_bucketed's wire bytes EQUAL zero2's (1_147_790 both —
#   bucketing coalesces instructions, the gradient bytes on the wire
#   are conserved);
# - the speculative [slots, K+1] verify steps cost ~(K+1)x the plain
#   step's FLOPs (3_788_766 / 995_578 ≈ 3.8 at K=3: verification is
#   K+1 tokens of real work in one dispatch, not free);
# - the ddp/zero1/zero2/zero3 wire bytes match profiling/comm_model's
#   analytic ring formulas (ddp: 2·G·(N-1)/N = 765_191 at G≈437 KiB,
#   N=8).
# Wire pins are per-chip ring-transfer bytes; 0 means every collective
# in the program (if any) spans a single-member group.
# Re-pin procedure: docs/ANALYSIS.md §7.
STABLE_COST_BUDGETS: dict[str, CostBudget] = {
    "baseline": CostBudget(
        max_flops=183_932_936, max_hbm_bytes=169_741_764,
        max_wire_bytes=0,
    ),
    "train_guard": CostBudget(
        max_flops=185_035_563, max_hbm_bytes=171_955_291,
        max_wire_bytes=0,
    ),
    "ddp": CostBudget(
        max_flops=24_937_385, max_hbm_bytes=23_071_428,
        max_wire_bytes=765_191,
    ),
    "ddp_bf16": CostBudget(
        max_flops=25_543_593, max_hbm_bytes=24_730_316,
        max_wire_bytes=765_191,
        note="wire bytes EQUAL f32 ddp's: grads are reduced in f32 "
             "(master-weight contract) even under bf16 compute",
    ),
    "fsdp": CostBudget(
        max_flops=23_024_363, max_hbm_bytes=15_904_440,
        max_wire_bytes=1_114_638,
    ),
    "zero2": CostBudget(
        max_flops=23_024_443, max_hbm_bytes=21_446_912,
        max_wire_bytes=1_147_790,
    ),
    "fsdp_prefetch": CostBudget(
        max_flops=23_504_197, max_hbm_bytes=14_366_932,
        max_wire_bytes=1_114_862,
        note="wire ~= plain fsdp (224 B of window bookkeeping): the "
             "prefetch schedule moves WHEN gathers run, not how much",
    ),
    "zero2_bucketed": CostBudget(
        max_flops=23_024_550, max_hbm_bytes=22_520_684,
        max_wire_bytes=1_147_790,
        note="wire bytes EQUAL zero2's: bucketing coalesces 16 "
             "reduce-scatters into 2, the gradient bytes are conserved",
    ),
    "tp": CostBudget(
        max_flops=58_934_440, max_hbm_bytes=138_191_808,
        max_wire_bytes=983_046,
    ),
    "ring": CostBudget(
        max_flops=48_101_694, max_hbm_bytes=59_218_736,
        max_wire_bytes=1_245_702,
    ),
    "ulysses": CostBudget(
        max_flops=48_547_534, max_hbm_bytes=41_490_092,
        max_wire_bytes=950_790,
    ),
    "ep": CostBudget(
        max_flops=275_422_141, max_hbm_bytes=85_402_756,
        max_wire_bytes=1_441_548,
    ),
    "pipeline": CostBudget(
        max_flops=123_603_517, max_hbm_bytes=125_967_090,
        max_wire_bytes=201_228,
    ),
    "pipeline_1f1b": CostBudget(
        max_flops=312_516_369, max_hbm_bytes=205_151_114,
        max_wire_bytes=365_064,
    ),
    "decode_prefill": CostBudget(
        max_flops=1_870_946, max_hbm_bytes=2_286_998,
        max_wire_bytes=0,
    ),
    "decode_step": CostBudget(
        max_flops=248_741, max_hbm_bytes=1_245_366,
        max_wire_bytes=0,
    ),
    "zero3_decode_prefetch": CostBudget(
        max_flops=160_202, max_hbm_bytes=1_588_952,
        max_wire_bytes=351_750,
        allow_lower_bound=True,
        note="decode_run's token while exits early on EOS — the trip "
             "count is data-dependent, so XLA records none and the "
             "body is counted ONCE; the pin certifies the per-iteration "
             "cost shape (setup + one token step), not a full "
             "generation",
    ),
    "decode_batched_prefill": CostBudget(
        max_flops=1_875_603, max_hbm_bytes=2_487_782,
        max_wire_bytes=0,
    ),
    "decode_batched_step": CostBudget(
        max_flops=995_438, max_hbm_bytes=3_412_262,
        max_wire_bytes=0,
    ),
    "decode_batched_step_tp": CostBudget(
        max_flops=357_974, max_hbm_bytes=1_047_718,
        max_wire_bytes=6_144,
    ),
    "decode_paged_prefill": CostBudget(
        max_flops=1_874_550, max_hbm_bytes=3_968_747,
        max_wire_bytes=0,
    ),
    "decode_paged_step": CostBudget(
        max_flops=995_578, max_hbm_bytes=3_411_430,
        max_wire_bytes=0,
    ),
    "decode_paged_prefill_q8": CostBudget(
        max_flops=1_918_006, max_hbm_bytes=2_106_172,
        max_wire_bytes=0,
        note="HBM 0.53x the f32 paged prefill: int8 pages move int8 "
             "bytes; the extra flops are the quantize/dequantize math",
    ),
    "decode_paged_step_q8": CostBudget(
        max_flops=1_031_642, max_hbm_bytes=1_935_015,
        max_wire_bytes=0,
        note="HBM 0.57x the f32 paged step: the cache-read traffic "
             "shrinks by the page pool's 0.3125x, diluted by the "
             "unquantized weights/activations",
    ),
    "decode_batched_step_tp_q8": CostBudget(
        max_flops=360_662, max_hbm_bytes=913_846,
        max_wire_bytes=6_144,
        note="wire bytes EQUAL the f32 tp step's: the Megatron psums "
             "reduce f32 activations either way; int8 slims HBM, not "
             "the wire",
    ),
    "decode_batched_spec_step": CostBudget(
        max_flops=3_788_230, max_hbm_bytes=5_724_974,
        max_wire_bytes=0,
    ),
    "decode_paged_spec_step": CostBudget(
        max_flops=3_788_766, max_hbm_bytes=5_725_198,
        max_wire_bytes=0,
        note="~3.8x the plain paged step's flops at K=3: the [slots, "
             "K+1] verify forward is K+1 tokens of real math in one "
             "dispatch",
    ),
    "decode_batched_step_tp_spec": CostBudget(
        max_flops=1_238_374, max_hbm_bytes=1_814_062,
        max_wire_bytes=24_576,
        note="wire = 4x the plain tp step's 6_144: the psum payload is "
             "[slots, K+1, ...] — speculative verify widens the "
             "collective by exactly K+1",
    ),
    "decode_paged_prefill_lora": CostBudget(
        max_flops=1_977_084, max_hbm_bytes=4_128_576,
        max_wire_bytes=0,
    ),
    "decode_paged_step_lora": CostBudget(
        max_flops=1_046_878, max_hbm_bytes=3_540_842,
        max_wire_bytes=0,
    ),
    "decode_batched_step_tp_lora": CostBudget(
        max_flops=390_074, max_hbm_bytes=1_133_610,
        max_wire_bytes=6_144,
    ),
    "decode_paged_kv_export": CostBudget(
        max_flops=12, max_hbm_bytes=81_936,
        max_wire_bytes=0,
        note="a pure gather: ~zero flops, and the HBM bill is the pool "
             "read + page write — any math appearing here is a bug",
    ),
    "decode_paged_kv_import": CostBudget(
        max_flops=4_190, max_hbm_bytes=328_092,
        max_wire_bytes=0,
        note="a pure scatter at freshly allocated page ids; flops are "
             "the table-indexing arithmetic, not tensor math",
    ),
    "decode_paged_kv_import_q8": CostBudget(
        max_flops=4_518, max_hbm_bytes=103_176,
        max_wire_bytes=0,
        note="HBM 0.31x the f32 import: int8 pages move int8 bytes, "
             "and the zero q8-cast pin keeps it that way",
    ),
    "decode_paged_kv_import_tp": CostBudget(
        max_flops=2_142, max_hbm_bytes=164_252,
        max_wire_bytes=0,
        note="wire bytes ZERO under tensor=2: each shard scatters its "
             "own head slice — a collective here would silently "
             "multiply the handoff's wire cost",
    ),
    "ddp_pjit": CostBudget(
        max_flops=24_735_275, max_hbm_bytes=23_540_208,
        max_wire_bytes=822_535,
    ),
    "fsdp_pjit": CostBudget(
        max_flops=23_073_182, max_hbm_bytes=29_725_492,
        max_wire_bytes=3_567_767,
        note="3.2x the explicit fsdp's wire: GSPMD re-gathers per use "
             "site where the explicit schedule gathers once per layer "
             "— the quantified cost of leaving placement to the "
             "partitioner",
    ),
    "zero2_pjit": CostBudget(
        max_flops=23_108_497, max_hbm_bytes=33_532_884,
        max_wire_bytes=2_770_551,
    ),
    "tp_pjit": CostBudget(
        max_flops=58_901_672, max_hbm_bytes=137_143_324,
        max_wire_bytes=786_468,
    ),
    "ring_pjit": CostBudget(
        max_flops=47_477_847, max_hbm_bytes=39_873_764,
        max_wire_bytes=1_445_382,
    ),
    "ep_pjit": CostBudget(
        max_flops=402_948_676, max_hbm_bytes=104_013_404,
        max_wire_bytes=3_281_922,
    ),
}


def cost_budget_for(case: str) -> CostBudget:
    """The pinned STABLE_COST_BUDGETS entry for ``case``.

    KeyError (with the fix spelled out) when the case has no pin: every
    registered program must carry a cost budget, so a new program cannot
    ship with unaudited throughput resources.
    """
    try:
        return STABLE_COST_BUDGETS[case]
    except KeyError:
        raise KeyError(
            f"no pinned cost budget for registered case {case!r} — "
            "measure it (scripts/audit.py --case "
            f"{case} --only cost --json r.json, read "
            "summary.cost) and add a STABLE_COST_BUDGETS entry "
            "(docs/ANALYSIS.md §7 documents the re-pin procedure)"
        ) from None


def expected_budget(
    mesh_cfg: MeshConfig, model_cfg: ModelConfig | None = None
) -> CollectiveBudget:
    """The collective contract a (mesh, model) combination implies.

    Mirrors the strategy implementations: required ops are the collectives
    each active axis/strategy writes (or AD transposes into existence);
    everything no active axis can legitimately produce is forbidden.
    all-reduce is tolerated whenever ANY axis is active — every path
    all-reduces the scalar loss/grad-norm metrics across its axes.
    """
    required: set[str] = set()
    notes: list[str] = []

    dp_active = mesh_cfg.data > 1
    fsdp_active = mesh_cfg.fsdp > 1
    if fsdp_active and mesh_cfg.strategy == "full_shard":
        # ZeRO-3: just-in-time param all-gather; its AD transpose IS the
        # gradient reduce-scatter.
        required |= {"all-gather", "reduce-scatter"}
        notes.append("fsdp/full_shard: gather params + scatter grads")
    elif fsdp_active and mesh_cfg.strategy == "shard_grad_op":
        # ZeRO-2: grads reduce-scattered onto opt-state shards; params
        # re-materialise via a psum of disjoint slices (an all-reduce).
        required |= {"reduce-scatter"}
        notes.append("fsdp/shard_grad_op: scatter grads")
    elif fsdp_active and mesh_cfg.strategy == "shard_opt":
        # ZeRO-1: grads replicated-all-reduced like DDP.
        required |= {"all-reduce"}
        notes.append("fsdp/shard_opt: all-reduce grads")
    elif fsdp_active:  # no_shard with an fsdp axis: pure data parallelism
        required |= {"all-reduce"}
    if dp_active:
        required |= {"all-reduce"}
        notes.append("data: all-reduce grads at the accumulation boundary")
    if mesh_cfg.tensor > 1:
        # Megatron f/g conjugates: psum after every row-parallel projection.
        required |= {"all-reduce"}
        notes.append("tensor: psum at parallel-region boundaries")
    if mesh_cfg.seq > 1:
        if model_cfg is not None and model_cfg.seq_impl == "ulysses":
            required |= {"all-to-all"}
            notes.append("seq/ulysses: head<->sequence all-to-all")
        else:
            required |= {"collective-permute"}
            notes.append("seq/ring: KV ring ppermute")
    if mesh_cfg.expert > 1:
        required |= {"all-to-all"}
        notes.append("expert: token dispatch all-to-all")
    if mesh_cfg.pipe > 1:
        required |= {"collective-permute"}
        notes.append("pipe: stage-boundary shifts")

    if not required:
        return NO_COLLECTIVES

    # Scalar metrics (loss, grad_norm) are all-reduced over every active
    # axis on every path, so all-reduce can appear even when no strategy
    # requires it for gradients.
    tolerated = {"all-reduce"}
    forbidden = set(HLO_COLLECTIVES) - required - tolerated
    return CollectiveBudget(
        required=frozenset(required),
        forbidden=frozenset(forbidden),
        note="; ".join(notes),
    )


def check_async_overlap(
    pairs: list[AsyncCollective],
    min_compute: int,
) -> list[Finding]:
    """Assert every async collective start/done pair has compute scheduled
    between it (the overlap contract of the prefetch schedule).

    ``pairs``: analysis/hlo.async_collective_pairs over the compiled
    module. A pair with fewer than ``min_compute`` compute instructions
    between start and done is async in name only — the scheduler found
    nothing to hide the transfer under, so its full latency is exposed
    (error). An EMPTY pair list is reported as info, never success: sync
    backends (XLA:CPU) emit no -start/-done forms at all, and a green
    check that verified nothing would be coverage theater.
    """
    if not pairs:
        return [
            Finding(
                checker="collectives",
                code="no-async-collectives",
                severity="info",
                message=(
                    "overlap contract requested but the compiled module "
                    "schedules no async start/done pairs (sync-collective "
                    "backend, e.g. XLA:CPU) — overlap is UNVERIFIED here; "
                    "re-audit on a TPU/GPU backend for schedule evidence"
                ),
            )
        ]
    findings: list[Finding] = []
    for pair in pairs:
        if pair.compute_between < min_compute:
            findings.append(
                Finding(
                    checker="collectives",
                    code="exposed-async-collective",
                    severity="error",
                    message=(
                        f"{pair.start!r}/{pair.done!r}: only "
                        f"{pair.compute_between} compute instruction(s) "
                        f"scheduled between start and done "
                        f"(contract: >= {min_compute}) — the "
                        f"{pair.opcode} latency is exposed, not hidden"
                    ),
                    detail={
                        "opcode": pair.opcode,
                        "start": pair.start,
                        "done": pair.done,
                        "compute_between": pair.compute_between,
                        "min_compute": min_compute,
                    },
                )
            )
    return findings


def check_budget(
    found: dict[str, list[str]],
    budget: CollectiveBudget,
    *,
    classify=None,
) -> list[Finding]:
    """Diff the collectives a compiled program emits against its budget.

    ``found``: {base_opcode: [instruction names]} from
    analysis.hlo.collective_instructions. ``classify``: optional
    name -> category function (profiling.trace_analysis.classify_op);
    when given, every emitted collective instruction name must classify as
    "communication" — the guarantee that trace analysis will account for
    it (tests/test_hlo_collectives.py assertion 1).
    """
    findings: list[Finding] = []
    present = set(found)

    for op in sorted(budget.required - present):
        findings.append(
            Finding(
                checker="collectives",
                code="missing-collective",
                severity="error",
                message=(
                    f"strategy promises {op!r} but the compiled program "
                    f"never emits it (found: {sorted(present) or 'none'})"
                ),
                detail={"opcode": op, "found": sorted(present)},
            )
        )
    for op in sorted(budget.forbidden & present):
        findings.append(
            Finding(
                checker="collectives",
                code="forbidden-collective",
                severity="error",
                message=(
                    f"{op!r} appears {len(found[op])}x but the strategy "
                    "has no business emitting it"
                ),
                detail={"opcode": op, "instructions": found[op]},
            )
        )
    for op, cap in sorted(budget.max_counts.items()):
        n = len(found.get(op, []))
        if n > cap:
            findings.append(
                Finding(
                    checker="collectives",
                    code="budget-exceeded",
                    severity="error",
                    message=f"{op!r}: {n} instructions > budget of {cap}",
                    detail={
                        "opcode": op,
                        "count": n,
                        "budget": cap,
                        "instructions": found.get(op, []),
                    },
                )
            )
    if classify is not None:
        for op, names in sorted(found.items()):
            for name in names:
                cat = classify(name)
                if cat != "communication":
                    findings.append(
                        Finding(
                            checker="collectives",
                            code="unclassified-collective",
                            severity="error",
                            message=(
                                f"trace classifier labels {name!r} as "
                                f"{cat!r}, not 'communication' — trace "
                                "accounting would miscount this op"
                            ),
                            detail={"instruction": name, "category": cat},
                        )
                    )
    return findings
