"""Collective and memory budgets: what a program is ALLOWED to emit/hold.

Generalises the hard-coded per-strategy assertions of
tests/test_hlo_collectives.py into a reusable contract object:

- ``required``: base opcodes that MUST appear (the collectives the
  strategy's design promises — FSDP gathers+scatters, DDP all-reduces,
  ring permutes, EP all-to-alls);
- ``forbidden``: opcodes that must NOT appear (a sharding edit that sneaks
  an all-gather into a DDP step is exactly the silent regression this
  subsystem exists to catch);
- ``max_counts``: optional per-opcode instruction-count ceilings for
  programs whose collective count is part of the perf contract (e.g. ONE
  gradient all-reduce at the accumulation boundary).

``expected_budget`` derives the contract for a MeshConfig the same way the
strategies themselves are written (parallel/explicit.py, parallel/pipeline.py).

``MemoryBudget`` is the peer contract for bytes (analysis/memory.py's
static peak-HBM estimate): pinned ``max_live_bytes`` ceilings per
registered program, a hard cap on the bytes a donated input may fail to
alias (``check_memory`` names the parameter when XLA rejects a donation),
and an optional ceiling on the donated buffer itself (the int8-pool
contract — an upcast to f32 triples the pool and must fail the audit).
"""

from __future__ import annotations

import dataclasses

from pytorch_distributed_tpu.analysis.hlo import (
    HLO_COLLECTIVES,
    AsyncCollective,
)
from pytorch_distributed_tpu.analysis.report import Finding
from pytorch_distributed_tpu.config import MeshConfig, ModelConfig


@dataclasses.dataclass(frozen=True)
class CollectiveBudget:
    required: frozenset = frozenset()
    forbidden: frozenset = frozenset()
    max_counts: dict = dataclasses.field(default_factory=dict)
    note: str = ""
    # Overlap contract: when not None, every async collective
    # start/done pair the compiled module schedules must have at least
    # this many compute instructions between start and done
    # (analysis/hlo.async_collective_pairs) — the machine-checkable form
    # of "the transfer is hidden under compute, not just async-shaped".
    # Backends that emit synchronous collectives (XLA:CPU) produce no
    # pairs; the check then reports an info note instead of passing
    # silently (check_async_overlap).
    async_min_compute: int | None = None

    def __post_init__(self) -> None:
        object.__setattr__(self, "required", frozenset(self.required))
        object.__setattr__(self, "forbidden", frozenset(self.forbidden))
        for op in self.required | self.forbidden | set(self.max_counts):
            if op not in HLO_COLLECTIVES:
                raise ValueError(
                    f"unknown collective opcode {op!r}; known: "
                    f"{HLO_COLLECTIVES}"
                )
        overlap = self.required & self.forbidden
        if overlap:
            raise ValueError(
                f"opcodes both required and forbidden: {sorted(overlap)}"
            )


NO_COLLECTIVES = CollectiveBudget(
    forbidden=frozenset(HLO_COLLECTIVES),
    note="single-device program: any collective is a bug",
)


# Instruction-count ceilings for the registered cases whose collective
# count IS the perf contract, measured once on the tiny registry models
# (XLA:CPU, jax 0.4.37) and pinned. The numbers are per-HLO-module
# instruction counts, not logical collectives: XLA emits one all-reduce
# per psum operand, so DDP's "ONE gradient all-reduce" (a single variadic
# psum over the 15-leaf grad tree) plus the loss/metric reductions lands
# at 17 instructions; ZeRO-3's just-in-time gathers are per-leaf, per
# direction (forward gather + remat re-gather in backward), and its
# reduce-scatters are the gathers' AD transposes. A future edit that
# re-gathers params twice, loses the accumulate-locally/reduce-once
# structure, or sneaks a second grad reduction blows the ceiling.
#
# The latency-hiding schedule cases (PR 3):
# - fsdp_prefetch (prefetch_buffers=1 on the 2-layer registry model =
#   one 2-layer window): the window body textually contains W=2 copies
#   of each per-leaf gather/scatter, so the STATIC instruction count
#   roughly doubles while the DYNAMIC per-step collective count is
#   unchanged (W x per-body collectives x L/W trip count). The ceiling
#   pins that static shape — growth past it means the window gained a
#   third gather of the same leaf or lost the re-gather structure.
# - zero2_bucketed (rs_buckets=2): the per-leaf boundary psum_scatters
#   coalesce into exactly rs_buckets bucket collectives — THE schedule
#   contract; a 3rd reduce-scatter means bucketing silently broke.
# - zero3_decode_prefetch (the serving engine's ZeRO-3 decode_run,
#   prefetch_buffers=1 on the 2-layer registry model = one 2-layer
#   window): the partitioner's per-leaf layer gathers appear W=2 times
#   in the window body plus the up-front non-block gathers; growth past
#   the ceiling means a layer's shards started gathering twice per use
#   (or the embedding/head gathers moved inside the token loop). The
#   all-reduces are the partitioner's logit/softmax reductions.
STABLE_MAX_COUNTS: dict[str, dict[str, int]] = {
    "ddp": {"all-reduce": 17},
    "fsdp": {"all-gather": 27, "reduce-scatter": 16, "all-reduce": 2},
    "fsdp_prefetch": {
        "all-gather": 51, "reduce-scatter": 28, "all-reduce": 2,
    },
    "zero2_bucketed": {"reduce-scatter": 2, "all-reduce": 18},
    "zero3_decode_prefetch": {"all-gather": 28, "all-reduce": 11},
    # Slot-batched TP decode step (serving/engine.BatchedDecodeEngine):
    # exactly the scanned block body's two Megatron psums (attention
    # c_proj + MLP c_proj), emitted ONCE each thanks to the layer scan —
    # and, because every per-row quantity (pos, fold, sampling params,
    # active pattern) is a traced operand, this count is INVARIANT to how
    # many rows are active: admissions/retirements never touch the
    # program. Growth means per-row handling leaked a collective (e.g.
    # sampling started psumming per row) or the scan unrolled.
    "decode_batched_step_tp": {"all-reduce": 2},
}


def pin_max_counts(budget: CollectiveBudget, case: str) -> CollectiveBudget:
    """``budget`` with the STABLE_MAX_COUNTS ceilings for ``case``."""
    counts = STABLE_MAX_COUNTS[case]
    return dataclasses.replace(
        budget,
        max_counts={**budget.max_counts, **counts},
        note=f"{budget.note}; max_counts pinned ({case})".strip("; "),
    )


@dataclasses.dataclass(frozen=True)
class MemoryBudget:
    """Byte ceilings for one compiled program's static memory estimate.

    ``max_live_bytes``: ceiling on the liveness-scan peak
    (memory.MemoryEstimate.peak_live_bytes). Pinned per registered case in
    ``STABLE_MEMORY_BUDGETS`` the way STABLE_MAX_COUNTS pins collective
    counts: measured once on the tiny registry models and frozen, so a
    regression that doubles a live buffer blows the ceiling.
    ``max_unaliased_donated_bytes``: how many bytes of DONATED input XLA
    may fail to alias before the audit errors. 0 for the serving engines
    (in-place cache reuse IS the contract); a measured allowance for
    training cases that tolerate the odd reshaped optimizer slot.
    ``max_donated_bytes``: optional ceiling on the donated argument's own
    size — the quantized-pool contract (an int8 page pool silently upcast
    to f32 is ~4x these bytes and must fail loudly, independent of what
    the rest of the program does).
    """

    max_live_bytes: int | None = None
    max_unaliased_donated_bytes: int = 0
    max_donated_bytes: int | None = None
    note: str = ""


def check_memory(
    estimate,
    budget: MemoryBudget | None,
    *,
    donated_params: frozenset = frozenset(),
) -> tuple[list[Finding], dict]:
    """Diff a program's static memory estimate against its byte budget.

    ``estimate``: analysis/memory.estimate_memory over the compiled
    module text. ``donated_params``: the entry-parameter numbers the call
    site donated (audit.donated_param_numbers) — every one of them should
    appear in the accepted input_output_alias map; one that does not is
    double-buffered at runtime, and the finding NAMES it (parameter
    number, HLO name, shape, bytes) so the shape/dtype change that broke
    the alias is findable. Returns (findings, stats); a None budget
    records stats without judging them.
    """
    unaliased = sorted(donated_params - estimate.aliased_params)
    unaliased_bytes = estimate.param_bytes(unaliased)
    donated_bytes = estimate.param_bytes(donated_params)
    loop_peaks = {
        name: est.peak_live_bytes
        for name, est in estimate.loop_bodies().items()
    }
    stats = {
        "peak_live_bytes": estimate.peak_live_bytes,
        "raw_peak_bytes": estimate.raw_peak_bytes,
        "alias_saved_bytes": estimate.alias_saved_bytes,
        "parameter_bytes": estimate.parameter_bytes,
        "donated_bytes": donated_bytes,
        "unaliased_donated_bytes": unaliased_bytes,
        "unaliased_donated_params": unaliased[:16],
        "loop_body_peak_bytes": (
            max(loop_peaks.values()) if loop_peaks else 0
        ),
    }
    findings: list[Finding] = []
    if budget is None:
        return findings, stats
    stats["budget"] = {
        "max_live_bytes": budget.max_live_bytes,
        "max_unaliased_donated_bytes": budget.max_unaliased_donated_bytes,
        "max_donated_bytes": budget.max_donated_bytes,
        "note": budget.note,
    }

    if (
        budget.max_live_bytes is not None
        and estimate.peak_live_bytes > budget.max_live_bytes
    ):
        findings.append(
            Finding(
                checker="memory",
                code="memory-budget-exceeded",
                severity="error",
                message=(
                    f"static peak {estimate.peak_live_bytes:,} bytes > "
                    f"pinned ceiling {budget.max_live_bytes:,} — a live "
                    "buffer grew (lost alias, upcast, or a new "
                    "materialisation); re-pin only if the growth is a "
                    "deliberate contract change"
                ),
                detail={
                    "peak_live_bytes": estimate.peak_live_bytes,
                    "max_live_bytes": budget.max_live_bytes,
                },
            )
        )
    if unaliased_bytes > budget.max_unaliased_donated_bytes:
        for pn in unaliased:
            p = estimate.parameters.get(pn)
            findings.append(
                Finding(
                    checker="memory",
                    code="donated-param-not-aliased",
                    severity="error",
                    message=(
                        f"donated parameter {pn}"
                        + (
                            f" (%{p.name}: {p.shape}, {p.bytes:,} bytes)"
                            if p is not None else ""
                        )
                        + " has NO accepted output alias — XLA rejected "
                        "the donation, so those bytes are double-buffered "
                        "every call; find the shape/dtype change between "
                        "this input and the output meant to reuse it"
                    ),
                    detail={
                        "param_number": pn,
                        "param_name": p.name if p else None,
                        "shape": p.shape if p else None,
                        "bytes": p.bytes if p else None,
                        "allowance": budget.max_unaliased_donated_bytes,
                    },
                )
            )
    elif unaliased:
        findings.append(
            Finding(
                checker="memory",
                code="unaliased-donated-within-allowance",
                severity="info",
                message=(
                    f"{len(unaliased)} donated parameter(s) "
                    f"({unaliased_bytes:,} bytes) not aliased, within the "
                    f"budget's {budget.max_unaliased_donated_bytes:,}-byte "
                    "allowance"
                ),
                detail={"params": unaliased[:16],
                        "bytes": unaliased_bytes},
            )
        )
    if (
        budget.max_donated_bytes is not None
        and donated_bytes > budget.max_donated_bytes
    ):
        findings.append(
            Finding(
                checker="memory",
                code="donated-bytes-exceeded",
                severity="error",
                message=(
                    f"donated argument is {donated_bytes:,} bytes > "
                    f"pinned ceiling {budget.max_donated_bytes:,} — the "
                    "donated buffer itself grew (e.g. an int8 pool "
                    "silently upcast to full precision)"
                ),
                detail={
                    "donated_bytes": donated_bytes,
                    "max_donated_bytes": budget.max_donated_bytes,
                },
            )
        )
    return findings, stats


# Pinned static-memory ceilings per registered audit case, the bytes
# counterpart of STABLE_MAX_COUNTS: max_live_bytes is the measured
# liveness-scan peak of the compiled program on the tiny registry
# models (8 virtual CPU devices), frozen exactly — any growth is a
# regression until adjudicated and re-pinned (shrinkage passes: these
# are ceilings). max_donated_bytes pins the donated cache/pool argument
# itself for the serving cases, where its size IS the claim: the dense
# slot cache and the paged pool are both 65_536 B at the registry's
# equal-slots config (pool_pages*page_size == slots*max_len — paged
# wins by allocating FEWER pages, not smaller ones), and the int8 pool
# is 20_480 B = 0.3125x f32, exactly (head_dim+4)/(4*head_dim) at
# head_dim 16 (per-token f32 scales amortized over the head); an
# upcast to f32 lands at 65_536+ and fails donated-bytes-exceeded.
# max_unaliased_donated_bytes stays at its 0 default everywhere —
# measured: XLA accepts EVERY donated alias in every program at HEAD.
# Re-pin procedure: docs/ANALYSIS.md §6.
STABLE_MEMORY_BUDGETS: dict[str, MemoryBudget] = {
    "baseline": MemoryBudget(max_live_bytes=4_784_172),
    "train_guard": MemoryBudget(max_live_bytes=4_783_176),
    "ddp": MemoryBudget(max_live_bytes=2_458_408),
    "ddp_bf16": MemoryBudget(
        max_live_bytes=2_758_952,
        note="above f32 ddp: the f32 grad accumulator + bf16 activation "
             "copies coexist at the backward peak on this tiny model",
    ),
    "fsdp": MemoryBudget(max_live_bytes=709_868),
    "zero2": MemoryBudget(max_live_bytes=2_090_536),
    "fsdp_prefetch": MemoryBudget(
        max_live_bytes=733_152,
        note="the +1-layer prefetch window costs ~23 KiB over plain "
             "fsdp — the bounded-extra-live-bytes overlap claim",
    ),
    "zero2_bucketed": MemoryBudget(max_live_bytes=2_090_280),
    "tp": MemoryBudget(max_live_bytes=1_977_900),
    "ring": MemoryBudget(max_live_bytes=3_139_616),
    "ulysses": MemoryBudget(max_live_bytes=2_755_628),
    "ep": MemoryBudget(max_live_bytes=5_391_952),
    "pipeline": MemoryBudget(max_live_bytes=3_966_421),
    "pipeline_1f1b": MemoryBudget(
        max_live_bytes=1_540_180,
        note="~0.39x GPipe peak: 1F1B's bounded in-flight microbatches, "
             "reproduced from static bytes alone",
    ),
    "decode_prefill": MemoryBudget(
        max_live_bytes=554_156, max_donated_bytes=16_384,
    ),
    "decode_step": MemoryBudget(
        max_live_bytes=486_972, max_donated_bytes=16_384,
    ),
    "zero3_decode_prefetch": MemoryBudget(
        max_live_bytes=299_766, max_donated_bytes=16_384,
    ),
    "decode_batched_prefill": MemoryBudget(
        max_live_bytes=619_697, max_donated_bytes=65_536,
    ),
    "decode_batched_step": MemoryBudget(
        max_live_bytes=672_000, max_donated_bytes=65_536,
    ),
    "decode_batched_step_tp": MemoryBudget(
        max_live_bytes=197_760, max_donated_bytes=16_384,
    ),
    "decode_paged_prefill": MemoryBudget(
        max_live_bytes=681_213, max_donated_bytes=65_536,
    ),
    "decode_paged_step": MemoryBudget(
        max_live_bytes=672_000, max_donated_bytes=65_536,
    ),
    "decode_paged_prefill_q8": MemoryBudget(
        max_live_bytes=275_461, max_donated_bytes=20_480,
        note="int8 pool + per-token scales: 0.3125x the f32 pool at "
             "head_dim 16; an f32 upcast fails donated-bytes-exceeded",
    ),
    "decode_paged_step_q8": MemoryBudget(
        max_live_bytes=267_656, max_donated_bytes=20_480,
        note="int8 pool + per-token scales: 0.3125x the f32 pool at "
             "head_dim 16; an f32 upcast fails donated-bytes-exceeded",
    ),
    "decode_batched_step_tp_q8": MemoryBudget(
        max_live_bytes=125_952, max_donated_bytes=16_384,
    ),
    "decode_batched_spec_step": MemoryBudget(
        max_live_bytes=699_984, max_donated_bytes=65_536,
    ),
    "decode_paged_spec_step": MemoryBudget(
        max_live_bytes=700_016, max_donated_bytes=65_536,
    ),
    "decode_batched_step_tp_spec": MemoryBudget(
        max_live_bytes=211_920, max_donated_bytes=16_384,
    ),
    "decode_paged_prefill_lora": MemoryBudget(
        max_live_bytes=705_794, max_donated_bytes=65_536,
    ),
    "decode_paged_step_lora": MemoryBudget(
        max_live_bytes=696_612, max_donated_bytes=65_536,
    ),
    "decode_batched_step_tp_lora": MemoryBudget(
        max_live_bytes=213_156, max_donated_bytes=16_384,
    ),
    "ddp_pjit": MemoryBudget(max_live_bytes=2_458_808),
    "fsdp_pjit": MemoryBudget(max_live_bytes=1_094_776),
    "zero2_pjit": MemoryBudget(max_live_bytes=1_558_768),
    "tp_pjit": MemoryBudget(max_live_bytes=1_977_900),
    "ring_pjit": MemoryBudget(max_live_bytes=2_737_788),
    "ep_pjit": MemoryBudget(max_live_bytes=6_461_028),
}


def memory_budget_for(case: str) -> MemoryBudget:
    """The pinned STABLE_MEMORY_BUDGETS entry for ``case``.

    KeyError (with the fix spelled out) when the case has no pin: every
    registered program must carry a memory budget, so a new engine
    program cannot ship audit-unpinned.
    """
    try:
        return STABLE_MEMORY_BUDGETS[case]
    except KeyError:
        raise KeyError(
            f"no pinned memory budget for registered case {case!r} — "
            "measure it (scripts/audit.py --case "
            f"{case} --only memory --json r.json, read "
            "summary.memory) and add a STABLE_MEMORY_BUDGETS entry "
            "(docs/ANALYSIS.md §6 documents the re-pin procedure)"
        ) from None


def expected_budget(
    mesh_cfg: MeshConfig, model_cfg: ModelConfig | None = None
) -> CollectiveBudget:
    """The collective contract a (mesh, model) combination implies.

    Mirrors the strategy implementations: required ops are the collectives
    each active axis/strategy writes (or AD transposes into existence);
    everything no active axis can legitimately produce is forbidden.
    all-reduce is tolerated whenever ANY axis is active — every path
    all-reduces the scalar loss/grad-norm metrics across its axes.
    """
    required: set[str] = set()
    notes: list[str] = []

    dp_active = mesh_cfg.data > 1
    fsdp_active = mesh_cfg.fsdp > 1
    if fsdp_active and mesh_cfg.strategy == "full_shard":
        # ZeRO-3: just-in-time param all-gather; its AD transpose IS the
        # gradient reduce-scatter.
        required |= {"all-gather", "reduce-scatter"}
        notes.append("fsdp/full_shard: gather params + scatter grads")
    elif fsdp_active and mesh_cfg.strategy == "shard_grad_op":
        # ZeRO-2: grads reduce-scattered onto opt-state shards; params
        # re-materialise via a psum of disjoint slices (an all-reduce).
        required |= {"reduce-scatter"}
        notes.append("fsdp/shard_grad_op: scatter grads")
    elif fsdp_active and mesh_cfg.strategy == "shard_opt":
        # ZeRO-1: grads replicated-all-reduced like DDP.
        required |= {"all-reduce"}
        notes.append("fsdp/shard_opt: all-reduce grads")
    elif fsdp_active:  # no_shard with an fsdp axis: pure data parallelism
        required |= {"all-reduce"}
    if dp_active:
        required |= {"all-reduce"}
        notes.append("data: all-reduce grads at the accumulation boundary")
    if mesh_cfg.tensor > 1:
        # Megatron f/g conjugates: psum after every row-parallel projection.
        required |= {"all-reduce"}
        notes.append("tensor: psum at parallel-region boundaries")
    if mesh_cfg.seq > 1:
        if model_cfg is not None and model_cfg.seq_impl == "ulysses":
            required |= {"all-to-all"}
            notes.append("seq/ulysses: head<->sequence all-to-all")
        else:
            required |= {"collective-permute"}
            notes.append("seq/ring: KV ring ppermute")
    if mesh_cfg.expert > 1:
        required |= {"all-to-all"}
        notes.append("expert: token dispatch all-to-all")
    if mesh_cfg.pipe > 1:
        required |= {"collective-permute"}
        notes.append("pipe: stage-boundary shifts")

    if not required:
        return NO_COLLECTIVES

    # Scalar metrics (loss, grad_norm) are all-reduced over every active
    # axis on every path, so all-reduce can appear even when no strategy
    # requires it for gradients.
    tolerated = {"all-reduce"}
    forbidden = set(HLO_COLLECTIVES) - required - tolerated
    return CollectiveBudget(
        required=frozenset(required),
        forbidden=frozenset(forbidden),
        note="; ".join(notes),
    )


def check_async_overlap(
    pairs: list[AsyncCollective],
    min_compute: int,
) -> list[Finding]:
    """Assert every async collective start/done pair has compute scheduled
    between it (the overlap contract of the prefetch schedule).

    ``pairs``: analysis/hlo.async_collective_pairs over the compiled
    module. A pair with fewer than ``min_compute`` compute instructions
    between start and done is async in name only — the scheduler found
    nothing to hide the transfer under, so its full latency is exposed
    (error). An EMPTY pair list is reported as info, never success: sync
    backends (XLA:CPU) emit no -start/-done forms at all, and a green
    check that verified nothing would be coverage theater.
    """
    if not pairs:
        return [
            Finding(
                checker="collectives",
                code="no-async-collectives",
                severity="info",
                message=(
                    "overlap contract requested but the compiled module "
                    "schedules no async start/done pairs (sync-collective "
                    "backend, e.g. XLA:CPU) — overlap is UNVERIFIED here; "
                    "re-audit on a TPU/GPU backend for schedule evidence"
                ),
            )
        ]
    findings: list[Finding] = []
    for pair in pairs:
        if pair.compute_between < min_compute:
            findings.append(
                Finding(
                    checker="collectives",
                    code="exposed-async-collective",
                    severity="error",
                    message=(
                        f"{pair.start!r}/{pair.done!r}: only "
                        f"{pair.compute_between} compute instruction(s) "
                        f"scheduled between start and done "
                        f"(contract: >= {min_compute}) — the "
                        f"{pair.opcode} latency is exposed, not hidden"
                    ),
                    detail={
                        "opcode": pair.opcode,
                        "start": pair.start,
                        "done": pair.done,
                        "compute_between": pair.compute_between,
                        "min_compute": min_compute,
                    },
                )
            )
    return findings


def check_budget(
    found: dict[str, list[str]],
    budget: CollectiveBudget,
    *,
    classify=None,
) -> list[Finding]:
    """Diff the collectives a compiled program emits against its budget.

    ``found``: {base_opcode: [instruction names]} from
    analysis.hlo.collective_instructions. ``classify``: optional
    name -> category function (profiling.trace_analysis.classify_op);
    when given, every emitted collective instruction name must classify as
    "communication" — the guarantee that trace analysis will account for
    it (tests/test_hlo_collectives.py assertion 1).
    """
    findings: list[Finding] = []
    present = set(found)

    for op in sorted(budget.required - present):
        findings.append(
            Finding(
                checker="collectives",
                code="missing-collective",
                severity="error",
                message=(
                    f"strategy promises {op!r} but the compiled program "
                    f"never emits it (found: {sorted(present) or 'none'})"
                ),
                detail={"opcode": op, "found": sorted(present)},
            )
        )
    for op in sorted(budget.forbidden & present):
        findings.append(
            Finding(
                checker="collectives",
                code="forbidden-collective",
                severity="error",
                message=(
                    f"{op!r} appears {len(found[op])}x but the strategy "
                    "has no business emitting it"
                ),
                detail={"opcode": op, "instructions": found[op]},
            )
        )
    for op, cap in sorted(budget.max_counts.items()):
        n = len(found.get(op, []))
        if n > cap:
            findings.append(
                Finding(
                    checker="collectives",
                    code="budget-exceeded",
                    severity="error",
                    message=f"{op!r}: {n} instructions > budget of {cap}",
                    detail={
                        "opcode": op,
                        "count": n,
                        "budget": cap,
                        "instructions": found.get(op, []),
                    },
                )
            )
    if classify is not None:
        for op, names in sorted(found.items()):
            for name in names:
                cat = classify(name)
                if cat != "communication":
                    findings.append(
                        Finding(
                            checker="collectives",
                            code="unclassified-collective",
                            severity="error",
                            message=(
                                f"trace classifier labels {name!r} as "
                                f"{cat!r}, not 'communication' — trace "
                                "accounting would miscount this op"
                            ),
                            detail={"instruction": name, "category": cat},
                        )
                    )
    return findings
