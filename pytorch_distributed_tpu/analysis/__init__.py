"""Static analysis of compiled programs: the `xprog` audit pass.

Audits any jitted step function's jaxpr + optimized HLO without running
it: collective budgets per parallelism strategy, donation/aliasing,
dtype leaks, recompilation/host-sync hazards, the vma
replication/varying-axes checker for shard_map bodies (our own
``check_vma``, independent of the jax version), a static peak-HBM
liveness estimate diffed against pinned per-program byte budgets
(analysis/memory.py + MemoryBudget), and a static
FLOPs / HBM-traffic / wire-bytes cost estimate with a roofline step-time
projection, diffed against pinned per-program throughput budgets
(analysis/cost.py + CostBudget). See docs/ANALYSIS.md.

Entry points:
- ``audit_program(fn, args, budget) -> AuditReport`` — library API;
- ``scripts/audit.py --all`` — audit every registered strategy x model;
- the ``audit`` pytest fixture (analysis/pytest_plugin.py);
- ``python -m pytorch_distributed_tpu.analysis.repolint`` — repo-rule
  AST lint (CI).
"""

from pytorch_distributed_tpu.analysis.audit import (
    audit_program,
    check_donation,
    check_dtype,
    check_hazards,
)
from pytorch_distributed_tpu.analysis.budget import (
    NO_COLLECTIVES,
    STABLE_COST_BUDGETS,
    STABLE_MEMORY_BUDGETS,
    CollectiveBudget,
    CostBudget,
    MemoryBudget,
    check_budget,
    check_cost,
    check_memory,
    cost_budget_for,
    expected_budget,
    memory_budget_for,
)
from pytorch_distributed_tpu.analysis.cost import (
    ProgramCost,
    RooflineSpec,
    V5E_ROOFLINE,
    estimate_cost,
    project_step_time,
    projected_tok_s,
)
from pytorch_distributed_tpu.analysis.hlo import (
    HLO_COLLECTIVES,
    collective_counts,
    collective_instructions,
    parse_input_output_aliases,
)
from pytorch_distributed_tpu.analysis.memory import (
    MemoryEstimate,
    estimate_memory,
    parse_module,
    shape_bytes,
)
from pytorch_distributed_tpu.analysis.report import (
    AuditReport,
    Finding,
    reports_to_json,
)
from pytorch_distributed_tpu.analysis.vma_check import (
    VmaInterpreter,
    check_shard_map_eqn,
    check_vma_program,
    find_shard_map_eqns,
)

__all__ = [
    "AuditReport",
    "CollectiveBudget",
    "CostBudget",
    "Finding",
    "HLO_COLLECTIVES",
    "MemoryBudget",
    "MemoryEstimate",
    "NO_COLLECTIVES",
    "ProgramCost",
    "RooflineSpec",
    "STABLE_COST_BUDGETS",
    "STABLE_MEMORY_BUDGETS",
    "V5E_ROOFLINE",
    "VmaInterpreter",
    "audit_program",
    "check_budget",
    "check_cost",
    "check_donation",
    "check_dtype",
    "check_hazards",
    "check_memory",
    "check_shard_map_eqn",
    "check_vma_program",
    "collective_counts",
    "collective_instructions",
    "cost_budget_for",
    "estimate_cost",
    "estimate_memory",
    "expected_budget",
    "find_shard_map_eqns",
    "memory_budget_for",
    "parse_input_output_aliases",
    "parse_module",
    "project_step_time",
    "projected_tok_s",
    "reports_to_json",
    "shape_bytes",
]
