"""``audit_program``: static audit of a jitted step function.

Takes any jitted (state, ...) -> (...) entry point plus example arguments,
lowers + compiles it WITHOUT running it, and checks:

1. collective budget — the compiled HLO emits exactly the collectives the
   strategy's contract allows (analysis/budget.py);
2. donation — the state argument's buffers are input/output-aliased in the
   compiled module (donate_argnums was passed AND XLA accepted the
   aliases; losing either silently double-buffers params+opt state);
3. dtype leaks — no all-f32 matmuls in a program configured for bf16
   compute, no back-to-back convert chains on the hot path (jaxpr-level:
   XLA:CPU legalises bf16 dots to f32, so optimized HLO would lie here);
4. recompilation / host-sync hazards — host callbacks
   (``jax.debug.print`` / ``io_callback`` / ``pure_callback``) inside the
   hot loop, weak-typed (Python-scalar) arguments that retrace when their
   Python type changes;
5. vma (replication/varying-axes) — an abstract interpreter over every
   ``shard_map`` body's jaxpr that re-derives which mesh axes each value
   varies over and diffs the result against the out_specs: missing psums,
   out_spec races, redundant collectives, collectives under divergent
   control flow (analysis/vma_check.py). Our own replication checker,
   independent of whether the rig's jax ships ``check_vma``;
6. memory — a static peak-HBM estimate over the optimized HLO
   (analysis/memory.py: buffer sizes from instruction shapes, liveness
   from a linear scan, input_output_alias honored) diffed against the
   program's pinned ``MemoryBudget`` — and, with teeth beyond the
   donation check's intent-verification: every donated entry parameter
   that XLA did NOT alias is named (number, HLO name, shape, bytes), so
   a broken in-place cache contract fails as an error pointing at the
   exact buffer that got double-buffered;
7. cost — a static FLOPs / HBM-bytes-moved / collective-wire-bytes
   estimate over the same optimized HLO (analysis/cost.py: contraction
   math from dot shapes, dtype-aware traffic at fusion boundaries, ring
   wire accounting from replica_groups, loop bodies multiplied by static
   trip counts) diffed against the program's pinned ``CostBudget`` — the
   throughput counterpart of check 6, so a doubled matmul, an upcast
   page pool, or a fattened collective fails CI without hardware.

The checkers are pure functions over the lowered artifacts, so everything
runs on the CPU test rig (``JAX_PLATFORMS=cpu`` + virtual devices) against
the SAME HLO the TPU path compiles, modulo backend-specific late rewrites.
"""

from __future__ import annotations

import jax

from pytorch_distributed_tpu.analysis.budget import (
    CollectiveBudget,
    CostBudget,
    MemoryBudget,
    check_async_overlap,
    check_budget,
    check_cost,
    check_memory,
)
from pytorch_distributed_tpu.analysis.hlo import (
    aliased_param_numbers,
    async_collective_pairs,
    collective_instructions,
)
from pytorch_distributed_tpu.analysis.jaxpr_scan import JaxprSummary
from pytorch_distributed_tpu.analysis.report import AuditReport, Finding
from pytorch_distributed_tpu.analysis.vma_check import check_vma_program
from pytorch_distributed_tpu.profiling.trace_analysis import classify_op

ALL_CHECKS = (
    "collectives", "donation", "dtype", "hazards", "vma", "memory", "cost",
)


def _leaf_count(tree) -> int:
    return len(jax.tree.leaves(tree))


def donated_param_numbers(
    args: tuple, donate_argnums: tuple[int, ...]
) -> frozenset[int]:
    """Entry-parameter numbers the donated positional arguments flatten
    into. jit flattens arguments in order, so argument ``i``'s leaves
    occupy a contiguous run of parameter numbers — the same mapping
    check_donation diffs against the alias header and check_memory uses
    to name un-aliased donated buffers."""
    expected: set[int] = set()
    offset = 0
    for i, arg in enumerate(args):
        n = _leaf_count(arg)
        if i in donate_argnums:
            expected |= set(range(offset, offset + n))
        offset += n
    return frozenset(expected)


def _program_jaxpr(jitted, args):
    """Traced (closed) jaxpr of a jitted program. Prefers
    ``jitted.trace(*args)``, which respects static_argnums/static_argnames
    (``jax.make_jaxpr`` would feed tracers into the static slots and crash
    on e.g. the decode entry points); falls back to make_jaxpr for plain
    callables, and to None when neither can trace the signature."""
    if hasattr(jitted, "trace"):
        try:
            return jitted.trace(*args).jaxpr
        except Exception:
            pass
    try:
        return jax.make_jaxpr(jitted)(*args)
    except Exception:
        return None


def check_donation(
    hlo_text: str,
    args: tuple,
    donate_argnums: tuple[int, ...],
    *,
    memory_analysis=None,
    strict: bool = False,
) -> tuple[list[Finding], dict]:
    """Verify the donated arguments survived compilation as buffer aliases.

    jit flattens positional arguments in order, so argument ``i``'s leaves
    occupy a contiguous run of entry-parameter numbers; every one of them
    should appear in the module header's ``input_output_alias`` map. A
    missing run means donate_argnums was dropped at the call site; a
    partial run means XLA rejected some aliases (shape/dtype mismatch
    between the donated input and any output — the "donated buffer was not
    usable" warning made machine-checkable).

    ``strict``: a PARTIAL alias set is an error, not a warn. Training
    steps tolerate the odd rejected leaf (a reshaped optimizer slot is a
    wart, not a contract breach); for programs whose donation IS the
    perf contract — the serving engine's in-place KV cache — any
    non-aliased donated buffer silently double-buffers the largest
    tensor in the program and must fail the audit.
    """
    aliased = aliased_param_numbers(hlo_text)
    expected = set(donated_param_numbers(args, donate_argnums))

    stats = {
        "expected": len(expected),
        "aliased": len(expected & aliased),
        "donate_argnums": list(donate_argnums),
    }
    if memory_analysis is not None:
        stats["alias_bytes"] = int(memory_analysis.alias_size_in_bytes)
        stats["argument_bytes"] = int(memory_analysis.argument_size_in_bytes)

    findings: list[Finding] = []
    if not expected:
        return findings, stats
    missing = expected - aliased
    if len(missing) == len(expected):
        findings.append(
            Finding(
                checker="donation",
                code="not-donated",
                severity="error",
                message=(
                    "no donated-state buffer is aliased in the compiled "
                    "module — the jit call site lost donate_argnums, so "
                    "params + optimizer state are double-buffered"
                ),
                detail=stats,
            )
        )
    elif missing:
        findings.append(
            Finding(
                checker="donation",
                code="donation-rejected",
                severity="error" if strict else "warn",
                message=(
                    f"XLA rejected {len(missing)} of {len(expected)} "
                    "donated-state aliases (those buffers are "
                    "double-buffered); check for shape/dtype changes "
                    "between the donated input and the outputs"
                ),
                detail={**stats, "missing_params": sorted(missing)[:16]},
            )
        )
    return findings, stats


def check_dtype(
    summary: JaxprSummary,
    compute_dtype: str,
    *,
    allowed_f32_dots: int = 0,
) -> list[Finding]:
    """Flag f32 matmuls and redundant convert chains in a bf16 program.

    A dot whose output is f32 with bf16 inputs is FINE (MXU accumulation);
    the leak is a dot whose inputs are already f32 when the program is
    configured for bf16 compute — usually an upcast that snuck in ahead of
    the matmul and silently halves matmul throughput.
    """
    findings: list[Finding] = []
    if compute_dtype not in ("bfloat16", "float16"):
        return findings
    f32_dots = [
        d
        for d in summary.dots
        if d.in_dtypes
        and all(t == "float32" for t in d.in_dtypes)
    ]
    if len(f32_dots) > allowed_f32_dots:
        in_loop = sum(1 for d in f32_dots if d.in_loop)
        findings.append(
            Finding(
                checker="dtype",
                code="f32-dot-leak",
                severity="error",
                message=(
                    f"{len(f32_dots)} all-f32 matmul(s) in a "
                    f"{compute_dtype} program ({in_loop} inside the hot "
                    f"loop; {allowed_f32_dots} allowed) — an upcast ahead "
                    "of the matmul is defeating the low-precision config"
                ),
                detail={
                    "count": len(f32_dots),
                    "allowed": allowed_f32_dots,
                    "in_loop": in_loop,
                },
            )
        )
    chains = [c for c in summary.converts if c.chained]
    hot_chains = [c for c in chains if c.in_loop]
    if hot_chains:
        findings.append(
            Finding(
                checker="dtype",
                code="convert-chain",
                severity="warn",
                message=(
                    f"{len(hot_chains)} back-to-back convert chain(s) on "
                    "the hot path (e.g. bf16->f32->bf16): at least one "
                    "conversion is wasted bandwidth"
                ),
                detail={
                    "chains": [
                        f"{c.in_dtype}->{c.out_dtype}" for c in hot_chains
                    ][:16]
                },
            )
        )
    return findings


def check_q8_casts(
    summary: JaxprSummary, budget: dict[str, int]
) -> tuple[list[Finding], dict[str, int]]:
    """The dtype-leak check extended to the quantized serving path: pin
    the program's int8 cast counts to its DECLARED quantize/dequantize
    sites.

    A quantized program has an EXACT cast inventory — one f32->int8
    convert per cache append (quantize-on-write: K and V), one
    int8->float per cache read (dequant-on-gather) and per weight-only
    matmul (the in-register kernel upcast) — and the check is an
    equality, not a ceiling, because BOTH directions of drift are real
    bugs:

    - MORE converts than declared: a silent full-precision round-trip —
      a dequantized pool being re-quantized (lossy: every round-trip
      re-rounds), or an int8 tensor materialised wide ahead of a
      consumer that should read it narrow (the bandwidth quantization
      existed to save, spent invisibly);
    - FEWER converts than declared: the path silently stopped
      quantizing — e.g. a renamed param key drops a projection out of
      QUANT_WEIGHT_SUFFIXES and the engine serves full-precision
      weights while every quality budget trivially passes (the path IS
      f32). The inventory is the only thing that notices.

    The registry's q8 cases carry the measured budgets
    (``q8_cast_budget={"to_int8": n, "from_int8": m}``) the way
    max_counts pins collective ceilings. Returns (findings, observed
    counts) so the report's summary quotes the same numbers the
    findings were judged on.
    """
    to_i8 = [c for c in summary.converts if c.out_dtype == "int8"]
    from_i8 = [c for c in summary.converts if c.in_dtype == "int8"]
    counts = {"to_int8": len(to_i8), "from_int8": len(from_i8)}
    findings: list[Finding] = []

    def diff(key, n, kind, extra_msg, missing_msg):
        want = budget.get(key)
        if want is None or n == want:
            return
        findings.append(
            Finding(
                checker="dtype",
                code=(
                    f"q8-extra-{kind}" if n > want
                    else f"q8-missing-{kind}"
                ),
                severity="error",
                message=(
                    f"{n} {key.replace('_', ' ')} converts but the "
                    f"program declares {want} {kind} site(s): "
                    + (extra_msg if n > want else missing_msg)
                ),
                detail={"count": n, "declared": want},
            )
        )

    diff(
        "to_int8", counts["to_int8"], "quantize",
        "something re-quantizes already-quantized data — a silent f32 "
        "round-trip re-rounds (lossy) and pays full-precision bandwidth "
        "on the path int8 exists to slim",
        "a declared quantize site vanished — part of the cache append "
        "is being written full-precision (or not at all); the quantized "
        "layout and the program no longer agree",
    )
    diff(
        "from_int8", counts["from_int8"], "dequantize",
        "an int8 tensor is being materialised wide somewhere beyond the "
        "declared reads — full-precision bytes moving on the "
        "bandwidth-bound path",
        "a declared dequantize site vanished — a consumer stopped "
        "reading int8 (e.g. a weight silently left the quantized set), "
        "so the path is running full precision while the quality "
        "budgets trivially pass",
    )
    return findings, counts


def check_hazards(summary: JaxprSummary) -> list[Finding]:
    """Host-sync and recompilation hazards visible in the jaxpr."""
    findings: list[Finding] = []
    for cb in summary.callbacks:
        if cb.in_loop:
            findings.append(
                Finding(
                    checker="hazards",
                    code="callback-in-hot-loop",
                    severity="error",
                    message=(
                        f"{cb.primitive} inside a scan/while body: every "
                        "iteration round-trips to the host, serialising "
                        f"the loop ({cb.detail or 'no detail'})"
                    ),
                    detail={"primitive": cb.primitive, "what": cb.detail},
                )
            )
        else:
            findings.append(
                Finding(
                    checker="hazards",
                    code="host-callback",
                    severity="warn",
                    message=(
                        f"{cb.primitive} in the traced program: fine for "
                        "debugging, a host sync in production "
                        f"({cb.detail or 'no detail'})"
                    ),
                    detail={"primitive": cb.primitive, "what": cb.detail},
                )
            )
    if summary.weak_type_inputs:
        findings.append(
            Finding(
                checker="hazards",
                code="weak-typed-input",
                severity="warn",
                message=(
                    f"{len(summary.weak_type_inputs)} argument(s) traced "
                    "weak-typed (Python scalars): a later call with a "
                    "different Python numeric type retraces AND "
                    "recompiles; pass jnp arrays with explicit dtypes"
                ),
                detail={"avals": summary.weak_type_inputs[:8]},
            )
        )
    return findings


def audit_program(
    fn,
    args: tuple,
    budget: CollectiveBudget | None = None,
    *,
    label: str | None = None,
    donate_argnums: tuple[int, ...] = (0,),
    expect_donation: bool = True,
    donation_strict: bool = False,
    compute_dtype: str | None = None,
    allowed_f32_dots: int = 0,
    q8_cast_budget: dict[str, int] | None = None,
    checks: tuple[str, ...] = ALL_CHECKS,
    vma_allow: dict[str, str] | None = None,
    dtype_allow: dict[str, str] | None = None,
    memory_budget: MemoryBudget | None = None,
    cost_budget: CostBudget | None = None,
) -> AuditReport:
    """Audit a jitted program's jaxpr + optimized HLO without running it.

    ``fn``: a jitted callable (anything with ``.lower``; a plain function
    is wrapped in a bare ``jax.jit``, in which case set
    ``expect_donation=False`` since the wrapper donates nothing).
    ``args``: example arguments, already placed/sharded the way the real
    call site places them. ``budget``: the collective contract
    (analysis/budget.expected_budget derives one from a MeshConfig);
    None skips the budget diff but still records collective counts.
    ``compute_dtype``: the activation dtype the program is configured for
    (ModelConfig.dtype); dtype checks only engage for low-precision
    programs. ``donation_strict``: partial donation aliasing is an error
    (see check_donation — the serving-engine cache contract).
    ``q8_cast_budget``: {"to_int8": n, "from_int8": m} — a quantized
    program's declared cast inventory; extra converts in either
    direction are errors (check_q8_casts — a silent f32 round-trip on
    the int8 path).
    ``vma_allow``: {finding code: reason} — downgrade the named vma
    findings to info with the reason attached (the audit-level analogue of
    a repolint allow-comment: the decision stays visible in the report).
    ``dtype_allow``: same mechanism for dtype findings — an adjudicated
    convert chain (e.g. a deliberate f32 master-weight accumulate in a
    bf16 program) stays in the report as info with its reason, instead of
    tripping the ``--strict`` lane forever.
    ``memory_budget``: the program's pinned byte ceilings
    (budget.MemoryBudget / STABLE_MEMORY_BUDGETS); None still records the
    static estimate in summary["memory"] without judging it.
    ``cost_budget``: the program's pinned FLOPs/HBM/wire ceilings
    (budget.CostBudget / STABLE_COST_BUDGETS); None still records the
    static cost in summary["cost"] without judging it. The roofline
    projection recorded alongside treats the wire term as overlapped
    exactly when the collective budget carries an ``async_min_compute``
    contract.
    """
    unknown = set(checks) - set(ALL_CHECKS)
    if unknown:
        raise ValueError(f"unknown checks: {sorted(unknown)}")
    # repolint: allow(jit-donation-decision) — inspection-only wrapper;
    # donation is the audited call site's contract, and forcing it here
    # would change the very alias accounting being audited.
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)

    report = AuditReport(label=label or getattr(fn, "__name__", "program"))
    report.summary["platform"] = jax.default_backend()

    # The HLO-level checks need a full XLA compile; the jaxpr-level ones
    # (dtype/hazards/vma) only need a trace — so e.g.
    # ``scripts/audit.py --only vma`` runs compile-free.
    need_hlo = (
        "collectives" in checks
        or "memory" in checks
        or "cost" in checks
        or ("donation" in checks and expect_donation)
    )
    if need_hlo:
        compiled = jitted.lower(*args).compile()
        hlo_text = compiled.as_text()
        found = collective_instructions(hlo_text)
        report.summary["collective_counts"] = {
            op: len(names) for op, names in found.items()
        }
    if "collectives" in checks:
        # Overlap evidence: async start/done pairs and the compute the
        # schedule placed between them. Always recorded (budget or not);
        # enforced when the budget carries an async_min_compute contract.
        pairs = async_collective_pairs(hlo_text)
        report.summary["async_collectives"] = {
            "pairs": len(pairs),
            "exposed": sum(1 for p in pairs if p.compute_between == 0),
            "min_compute_between": (
                min((p.compute_between for p in pairs), default=None)
            ),
        }
    if "collectives" in checks and budget is not None:
        report.extend(check_budget(found, budget, classify=classify_op))
        report.summary["budget"] = {
            "required": sorted(budget.required),
            "forbidden": sorted(budget.forbidden),
            "max_counts": dict(budget.max_counts),
            "note": budget.note,
        }
        if budget.async_min_compute is not None:
            report.extend(
                check_async_overlap(pairs, budget.async_min_compute)
            )

    if "donation" in checks and expect_donation:
        try:
            ma = compiled.memory_analysis()
        except Exception:  # backend without the C API
            ma = None
        findings, stats = check_donation(
            hlo_text, args, donate_argnums, memory_analysis=ma,
            strict=donation_strict,
        )
        report.extend(findings)
        report.summary["donation"] = stats

    if "memory" in checks:
        from pytorch_distributed_tpu.analysis.memory import estimate_memory

        try:
            estimate = estimate_memory(hlo_text)
        except Exception as e:
            # An error, not a warn: a crashed estimator means the
            # program's byte ceilings are UNVERIFIED, and the memory CI
            # gate must not report it green.
            report.findings.append(
                Finding(
                    checker="memory",
                    code="memory-estimate-failed",
                    severity="error",
                    message=(
                        f"static memory estimator crashed on this "
                        f"program ({e!r}) — its byte budgets are "
                        "UNVERIFIED"
                    ),
                )
            )
        else:
            donated = (
                donated_param_numbers(args, donate_argnums)
                if expect_donation
                else frozenset()
            )
            # No pinned budget still enforces the DEFAULT contract
            # (MemoryBudget(): no live ceiling, zero unaliased donated
            # bytes) — a donated input XLA failed to alias is an error
            # naming the parameter even on unpinned programs; only a
            # budget with an explicit allowance relaxes it.
            mem_findings, mem_stats = check_memory(
                estimate,
                memory_budget if memory_budget is not None
                else MemoryBudget(),
                donated_params=donated,
            )
            report.extend(mem_findings)
            report.summary["memory"] = mem_stats

    if "cost" in checks:
        from pytorch_distributed_tpu.analysis.cost import (
            estimate_cost,
            project_step_time,
        )

        try:
            cost = estimate_cost(hlo_text)
        except Exception as e:
            # An error, not a warn: a crashed estimator means the
            # program's throughput ceilings are UNVERIFIED, and the cost
            # CI gate must not report it green.
            report.findings.append(
                Finding(
                    checker="cost",
                    code="cost-estimate-failed",
                    severity="error",
                    message=(
                        f"static cost estimator crashed on this program "
                        f"({e!r}) — its FLOPs/HBM/wire budgets are "
                        "UNVERIFIED"
                    ),
                )
            )
        else:
            cost_findings, cost_stats = check_cost(cost, cost_budget)
            report.extend(cost_findings)
            # Roofline projection at the default chip spec, wire term
            # overlapped only when the program carries a machine-checked
            # overlap contract (CollectiveBudget.async_min_compute).
            cost_stats["roofline"] = project_step_time(
                cost,
                overlapped_comm=(
                    budget is not None
                    and budget.async_min_compute is not None
                ),
            )
            report.summary["cost"] = cost_stats

    jaxpr = None
    summary = None
    if {"dtype", "hazards", "vma"} & set(checks):
        from pytorch_distributed_tpu.analysis.jaxpr_scan import scan_jaxpr

        jaxpr = _program_jaxpr(jitted, args)
        if jaxpr is None:
            # When the HLO checks also ran, partial coverage is noted as
            # info (the decode-family static-arg audits); when EVERY
            # requested check needed the jaxpr, the audit would be
            # vacuous — fail loudly so e.g. a `--only vma` CI gate
            # cannot go silently green on an unchecked program.
            vacuous = not need_hlo
            report.findings.append(
                Finding(
                    checker="hazards",
                    code="jaxpr-unavailable",
                    severity="error" if vacuous else "info",
                    message=(
                        "could not trace a jaxpr for this program "
                        "(static-argument signature the tracer cannot "
                        "re-enter); dtype/hazard/vma checks skipped"
                        + (
                            " — and no other check ran, so this audit "
                            "verified NOTHING" if vacuous else ""
                        )
                    ),
                )
            )
        elif {"dtype", "hazards"} & set(checks):
            # A scanner crash on one program must degrade to a finding,
            # not abort the whole `--all` run (the pre-refactor
            # _program_summary swallowed these into jaxpr-unavailable).
            try:
                summary = scan_jaxpr(jaxpr)
            except Exception as e:
                summary = None
                report.findings.append(
                    Finding(
                        checker="hazards",
                        code="jaxpr-scan-failed",
                        severity="warn",
                        message=(
                            f"jaxpr scanner crashed on this program "
                            f"({e!r}); dtype/hazard checks skipped"
                        ),
                    )
                )

    if jaxpr is not None and "vma" in checks:
        try:
            vma_findings, vma_summary = check_vma_program(jaxpr)
        except Exception as e:
            # An error, not a warn: a crashed replication checker means
            # the program is UNVERIFIED, and the vma CI gate must not
            # report it green.
            vma_findings, vma_summary = None, None
            report.findings.append(
                Finding(
                    checker="vma",
                    code="vma-check-failed",
                    severity="error",
                    message=(
                        f"vma checker crashed on this program ({e!r}) — "
                        "its replication invariants are UNVERIFIED"
                    ),
                )
            )
        if vma_findings is not None:
            allow = vma_allow or {}
            for f in vma_findings:
                if f.code in allow:
                    f = Finding(
                        checker=f.checker, code=f.code, severity="info",
                        message=f"{f.message} [allowed: {allow[f.code]}]",
                        detail=f.detail,
                    )
                report.findings.append(f)
            report.summary["vma"] = vma_summary

    if summary is not None:
        report.summary["dot_dtypes"] = summary.dot_dtype_histogram()
        report.summary["hazards"] = {
            "callbacks": len(summary.callbacks),
            "weak_type_inputs": len(summary.weak_type_inputs),
            "chained_converts": sum(
                1 for c in summary.converts if c.chained
            ),
        }
        if "dtype" in checks and compute_dtype is not None:
            allow = dtype_allow or {}
            for f in check_dtype(
                summary, compute_dtype, allowed_f32_dots=allowed_f32_dots
            ):
                if f.code in allow:
                    f = Finding(
                        checker=f.checker, code=f.code, severity="info",
                        message=f"{f.message} [allowed: {allow[f.code]}]",
                        detail=f.detail,
                    )
                report.findings.append(f)
        if "dtype" in checks and q8_cast_budget is not None:
            q8_findings, q8_counts = check_q8_casts(
                summary, q8_cast_budget
            )
            report.extend(q8_findings)
            report.summary["q8_casts"] = {
                **q8_counts, "budget": dict(q8_cast_budget),
            }
        if "hazards" in checks:
            report.extend(check_hazards(summary))

    return report
