"""Repo-rule AST lint: project invariants a reviewer should never have to
re-litigate.

Rules (each suppressible with ``# repolint: allow(<rule>) — why`` on the
FIRST or LAST line of the offending expression — i.e. the flagged line
itself or trailing the closing paren of a continued call — or in the
comment block above it; interior lines do not bind, so an allow on a
nested call cannot waive the enclosing one. The reason is REQUIRED — a
bare allow is itself a violation):

- ``jit-donation-decision`` — every ``jax.jit`` call site / decorator
  must either pass ``donate_argnums``/``donate_argnames`` or carry an
  allow-comment explaining why its inputs must survive. Losing donation on
  a step function silently double-buffers params + optimizer state; the
  decision must be explicit either way.
- ``host-sync-in-traced`` — no ``jax.device_get`` / ``np.asarray`` /
  ``np.array`` inside a traced (jitted) function body: at best a
  trace-time constant bake, at worst a per-call device sync.
- ``wallclock-in-traced`` — no ``time.time``/``time.perf_counter``/
  ``datetime.now`` inside traced code: it executes ONCE at trace time and
  the program forever reports that frozen instant.
- ``debug-callback-in-library`` — ``jax.debug.print`` / ``io_callback`` /
  ``jax.debug.callback`` in library code (``pytorch_distributed_tpu/``)
  must be allowlisted: each firing is a host round-trip
  (scripts/ and tests/ may debug freely).
- ``blocking-sync-in-tick`` — no blocking device reads
  (``jax.device_get`` / ``np.asarray`` / ``np.array`` / ``.item()`` /
  ``.block_until_ready()``) inside the serving scheduler's tick path
  (``pytorch_distributed_tpu/serving/``: step/run/_admit/_prefill_group/
  _chunk_prefill_tick/_decode_tick/_decode_tick_spec/_dispatch). Every
  such read stalls the scheduler until the device drains — the
  continuous-batching design keeps exactly ONE adjudicated sync per tick
  (the dispatch-boundary output read), and that one carries an
  allow-comment with its reason. These are HOST functions, so the
  traced-body rules above never see them.

Run: ``python -m pytorch_distributed_tpu.analysis.repolint [paths...]``
(default: the package + scripts/). Exit code 1 on any violation — wired
into CI next to the tier-1 tests.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import sys
from pathlib import Path

_ALLOW_RE = re.compile(r"#\s*repolint:\s*allow\(([\w\-]+)\)\s*(?:—|--|-)\s*\S")
_BARE_ALLOW_RE = re.compile(r"#\s*repolint:\s*allow\(([\w\-]+)\)")

RULES = (
    "jit-donation-decision",
    "host-sync-in-traced",
    "wallclock-in-traced",
    "debug-callback-in-library",
    "blocking-sync-in-tick",
)

# The serving scheduler's tick path: methods on the hot engine loop
# (serving/engine.py) between "requests wait" and "tokens stream out".
# A blocking device read anywhere in here serialises the whole tick.
_TICK_PATH_FUNCS = frozenset({
    "step", "run", "_admit", "_prefill_group", "_chunk_prefill_tick",
    "_decode_tick", "_decode_tick_spec", "_dispatch",
})
# Method attrs that force a device sync on whatever they are called on.
_SYNC_ATTRS = frozenset({"item", "block_until_ready"})


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute chains, 'jit' for Names, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_callable(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _jit_call_sites(tree: ast.AST) -> list[ast.Call]:
    """Every ``jax.jit(...)`` Call, including inside ``partial(jax.jit, ...)``."""
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if _is_jit_callable(node.func):
                sites.append(node)
            elif _dotted(node.func) in ("functools.partial", "partial"):
                if node.args and _is_jit_callable(node.args[0]):
                    sites.append(node)
    return sites


def _jit_argument_names(tree: ast.AST) -> set[str]:
    """Names of functions passed (positionally) to a jax.jit call in this
    module — their bodies are traced."""
    names = set()
    for call in _jit_call_sites(tree):
        args = call.args
        if _dotted(call.func) in ("functools.partial", "partial"):
            args = call.args[1:]
        for a in args[:1]:
            if isinstance(a, ast.Name):
                names.add(a.id)
    return names


def _traced_functions(tree: ast.AST) -> list[ast.FunctionDef]:
    """FunctionDefs whose bodies trace under jit: decorated with jax.jit
    (bare or via partial) or passed by name to a jax.jit call site."""
    jitted_names = _jit_argument_names(tree)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if _is_jit_callable(dec):
                out.append(node)
                break
            if isinstance(dec, ast.Call) and (
                _is_jit_callable(dec.func)
                or (
                    _dotted(dec.func) in ("functools.partial", "partial")
                    and dec.args
                    and _is_jit_callable(dec.args[0])
                )
            ):
                out.append(node)
                break
        else:
            if node.name in jitted_names:
                out.append(node)
    return out


def _allowed(
    lines: list[str], lineno: int, rule: str, end_lineno: int | None = None
) -> bool:
    """allow-comment (with a reason) anywhere on the flagged expression's
    line span, or in the contiguous comment block immediately above it.

    The span matters for continued/parenthesized calls: ast reports the
    violation at the opening line, but a human writes the allow as a
    trailing comment after the closing paren —

        step = jax.jit(
            fn, static_argnames=("n",),
        )  # repolint: allow(jit-donation-decision) — reason

    — so the expression's FIRST and LAST lines are both searched. Only
    those two (not every interior line): an allow trailing a nested call
    on an interior line binds to the nested violation, and letting it
    also waive the enclosing expression would silently suppress a
    decision nobody reasoned about."""
    last = max(lineno, end_lineno or lineno)
    for ln in {lineno, last}:
        if 1 <= ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m and m.group(1) == rule:
                return True
    ln = lineno - 1
    while 1 <= ln <= len(lines) and lines[ln - 1].lstrip().startswith("#"):
        m = _ALLOW_RE.search(lines[ln - 1])
        if m and m.group(1) == rule:
            return True
        ln -= 1
    return False


def _bare_allows(lines: list[str]) -> list[tuple[int, str]]:
    """allow-comments with no reason text (themselves violations)."""
    out = []
    for i, line in enumerate(lines, 1):
        m = _BARE_ALLOW_RE.search(line)
        if m and not _ALLOW_RE.search(line):
            out.append((i, m.group(1)))
    return out


_HOST_SYNC_CALLS = ("jax.device_get", "np.asarray", "np.array",
                    "numpy.asarray", "numpy.array")
_WALLCLOCK_CALLS = ("time.time", "time.perf_counter", "time.monotonic",
                    "datetime.now", "datetime.datetime.now")
_DEBUG_CALLS = ("jax.debug.print", "jax.debug.callback", "io_callback",
                "jax.experimental.io_callback")


def lint_source(
    source: str, path: str, *, library: bool = False,
    serving: bool | None = None,
) -> list[Violation]:
    lines = source.splitlines()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:  # pragma: no cover - repo code parses
        return [Violation("parse-error", path, e.lineno or 0, str(e))]

    violations: list[Violation] = []

    def add(
        rule: str, lineno: int, message: str, end_lineno: int | None = None
    ) -> None:
        if not _allowed(lines, lineno, rule, end_lineno):
            violations.append(Violation(rule, path, lineno, message))

    for lineno, rule in _bare_allows(lines):
        violations.append(
            Violation(
                rule, path, lineno,
                "allow-comment without a reason — write "
                "'# repolint: allow(rule) — why'",
            )
        )

    # Rule: jit-donation-decision
    for call in _jit_call_sites(tree):
        kwargs = {kw.arg for kw in call.keywords}
        if not kwargs & {"donate_argnums", "donate_argnames"}:
            add(
                "jit-donation-decision",
                call.lineno,
                "jax.jit without donate_argnums — donate the step state, "
                "or allowlist with the reason its inputs must survive",
                end_lineno=getattr(call, "end_lineno", None),
            )
    # Bare `@jax.jit` decorators are not Call nodes and can never pass
    # donate_argnums, so they need an allow-comment just the same.
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call) and _is_jit_callable(dec):
                    add(
                        "jit-donation-decision",
                        dec.lineno,
                        f"bare @jax.jit on {node.name!r} cannot pass "
                        "donate_argnums — use jax.jit(...) with a "
                        "donation decision, or allowlist with the reason",
                    )

    # Rules inside traced bodies.
    for fn in _traced_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name in _HOST_SYNC_CALLS:
                add(
                    "host-sync-in-traced",
                    node.lineno,
                    f"{name}() inside traced function {fn.name!r}: this "
                    "bakes a trace-time constant / forces a host sync",
                    end_lineno=getattr(node, "end_lineno", None),
                )
            elif name in _WALLCLOCK_CALLS:
                add(
                    "wallclock-in-traced",
                    node.lineno,
                    f"{name}() inside traced function {fn.name!r}: "
                    "evaluates once at trace time, frozen thereafter",
                    end_lineno=getattr(node, "end_lineno", None),
                )

    # Rule: blocking syncs in the serving tick path. Host code, so the
    # traced-body walk above is blind to it: a `.item()` in _admit is a
    # legal Python program that quietly drains the device every tick.
    if serving is None:
        serving = path.replace("\\", "/").startswith(
            "pytorch_distributed_tpu/serving/"
        )
    if serving:
        tick_fns = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in _TICK_PATH_FUNCS
        ]
        for fn in tick_fns:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _dotted(node.func)
                sync = None
                if name in _HOST_SYNC_CALLS or name == "jax.device_get":
                    sync = f"{name}()"
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                    and not node.args
                ):
                    sync = f".{node.func.attr}()"
                if sync is not None:
                    add(
                        "blocking-sync-in-tick",
                        node.lineno,
                        f"{sync} inside scheduler tick path "
                        f"{fn.name!r}: blocks the tick until the device "
                        "drains — keep the loop async and allowlist only "
                        "the adjudicated dispatch-boundary read",
                        end_lineno=getattr(node, "end_lineno", None),
                    )

    # Rule: debug callbacks in library code (anywhere in the module, traced
    # or not — library modules should not ship debug prints).
    if library:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in _DEBUG_CALLS:
                    add(
                        "debug-callback-in-library",
                        node.lineno,
                        f"{name}() in library code: a host round-trip per "
                        "firing — gate it or move it to scripts/",
                        end_lineno=getattr(node, "end_lineno", None),
                    )
    return violations


def lint_paths(paths: list[Path], repo_root: Path) -> list[Violation]:
    violations: list[Violation] = []
    for base in paths:
        files = [base] if base.is_file() else sorted(base.rglob("*.py"))
        for f in files:
            rel = f.relative_to(repo_root) if f.is_relative_to(repo_root) else f
            library = str(rel).startswith("pytorch_distributed_tpu")
            violations.extend(
                lint_source(f.read_text(), str(rel), library=library)
            )
    return violations


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    repo_root = Path(__file__).resolve().parents[2]
    if argv:
        paths = [Path(p).resolve() for p in argv]
    else:
        paths = [
            repo_root / "pytorch_distributed_tpu",
            repo_root / "scripts",
        ]
    violations = lint_paths(paths, repo_root)
    for v in violations:
        print(v)
    n = len(violations)
    print(
        f"repolint: {n} violation(s)" if n else "repolint: clean",
        file=sys.stderr,
    )
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
