"""Jaxpr-level program scan: dtypes, converts, callbacks, loop context.

The jaxpr is the right level for numerics and host-interaction checks:

- XLA:CPU legalises bf16 dots to f32 during HLO optimization, so the
  compiled text on the CPU rig misreports matmul dtypes; the jaxpr records
  what the program asked for on every platform.
- ``debug_callback`` / ``io_callback`` / ``pure_callback`` equations are
  explicit in the jaxpr but lower into infeed/outfeed plumbing that is hard
  to attribute in HLO.
- scan/while structure is still visible, so "inside the hot loop" is a
  well-defined predicate (after jit, the training step's accumulation scan
  and decode's sampling loop are the hot loops that matter).
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Any

import jax

try:  # jax >= 0.4.16 moved the public core surface under jax.extend
    from jax.extend.core import Literal  # type: ignore
except ImportError:  # pragma: no cover
    from jax.core import Literal  # type: ignore

# Primitives whose bodies execute repeatedly at run time (hot loops).
_LOOP_PRIMS = ("scan", "while", "fori_loop")
# Host-callback primitives: each firing is a device->host sync point.
_CALLBACK_PRIMS = ("debug_callback", "io_callback", "pure_callback")


@dataclasses.dataclass(frozen=True)
class DotRecord:
    """One dot_general / conv_general_dilated equation."""

    primitive: str
    out_dtype: str
    in_dtypes: tuple[str, ...]
    preferred_element_type: str | None
    in_loop: bool


@dataclasses.dataclass(frozen=True)
class ConvertRecord:
    out_dtype: str
    in_dtype: str
    in_loop: bool
    # The producing equation of this convert's operand is itself a convert
    # (an A->B->A or A->B->C chain: at least one of the two is wasted work
    # on the hot path).
    chained: bool


@dataclasses.dataclass(frozen=True)
class CallbackRecord:
    primitive: str
    in_loop: bool
    # Best-effort description (debug.print format string / callback repr).
    detail: str


@dataclasses.dataclass
class JaxprSummary:
    dots: list[DotRecord]
    converts: list[ConvertRecord]
    callbacks: list[CallbackRecord]
    # Input avals traced weak-typed: the caller passed Python scalars, so a
    # later call with a different Python type retraces and recompiles.
    weak_type_inputs: list[str]
    primitive_counts: Counter

    def dot_dtype_histogram(self) -> dict[str, int]:
        hist: Counter = Counter(d.out_dtype for d in self.dots)
        return dict(hist)


def _subjaxprs(eqn) -> list[Any]:
    subs = []
    for key, val in eqn.params.items():
        if hasattr(val, "jaxpr"):  # ClosedJaxpr
            subs.append(val.jaxpr)
        elif hasattr(val, "eqns"):  # bare Jaxpr
            subs.append(val)
        elif key == "branches":
            subs.extend(b.jaxpr if hasattr(b, "jaxpr") else b for b in val)
    return subs


def _callback_detail(eqn) -> str:
    for key in ("fmt", "callback", "debug_func"):
        if key in eqn.params:
            return repr(eqn.params[key])[:120]
    return ""


def scan_jaxpr(jaxpr) -> JaxprSummary:
    """Walk a (closed or bare) jaxpr recursively into every sub-jaxpr
    (pjit bodies, shard_map bodies, scan/while bodies, cond branches,
    custom_vjp/jvp call jaxprs) and summarise the audit-relevant facts."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    summary = JaxprSummary(
        dots=[],
        converts=[],
        callbacks=[],
        weak_type_inputs=[],
        primitive_counts=Counter(),
    )
    for var in jaxpr.invars:
        aval = getattr(var, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            summary.weak_type_inputs.append(str(aval))

    def walk(jx, in_loop: bool, convert_outvars: set):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            summary.primitive_counts[name] += 1
            if name in ("dot_general", "conv_general_dilated"):
                pet = eqn.params.get("preferred_element_type")
                summary.dots.append(
                    DotRecord(
                        primitive=name,
                        out_dtype=str(eqn.outvars[0].aval.dtype),
                        in_dtypes=tuple(
                            str(v.aval.dtype)
                            for v in eqn.invars
                            if hasattr(v, "aval")
                        ),
                        preferred_element_type=(
                            str(pet) if pet is not None else None
                        ),
                        in_loop=in_loop,
                    )
                )
            elif name == "convert_element_type":
                src = eqn.invars[0]
                summary.converts.append(
                    ConvertRecord(
                        out_dtype=str(eqn.outvars[0].aval.dtype),
                        in_dtype=str(src.aval.dtype),
                        in_loop=in_loop,
                        chained=(
                            not isinstance(src, Literal)
                            and src in convert_outvars
                        ),
                    )
                )
                convert_outvars.add(eqn.outvars[0])
            elif name in _CALLBACK_PRIMS:
                summary.callbacks.append(
                    CallbackRecord(
                        primitive=name,
                        in_loop=in_loop,
                        detail=_callback_detail(eqn),
                    )
                )
            loopish = any(name.startswith(p) for p in _LOOP_PRIMS)
            for sub in _subjaxprs(eqn):
                # Sub-jaxprs get a FRESH convert-producer scope: vars are
                # jaxpr-local, so carrying the outer set across the
                # boundary could only produce false identity matches.
                walk(sub, in_loop or loopish, set())
        return summary

    return walk(jaxpr, False, set())


def trace_summary(fn, args: tuple, kwargs: dict | None = None) -> JaxprSummary:
    """Trace ``fn`` (jitted or plain) on ``args`` and scan the jaxpr."""
    jaxpr = jax.make_jaxpr(fn)(*args, **(kwargs or {}))
    return scan_jaxpr(jaxpr)
