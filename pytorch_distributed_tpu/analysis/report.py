"""Audit findings and the report container shared by every checker."""

from __future__ import annotations

import dataclasses
import json
from typing import Any

SEVERITIES = ("error", "warn", "info")


def _fmt_bytes(n: int) -> str:
    if n >= 2**20:
        return f"{n / 2**20:.2f} MiB"
    if n >= 2**10:
        return f"{n / 2**10:.1f} KiB"
    return f"{n} B"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One audit observation.

    checker: which pass produced it ("collectives", "donation", "dtype",
             "hazards").
    code:    stable machine-readable identifier (e.g. "missing-collective").
    severity: "error" (the program violates its contract), "warn"
             (suspicious, judgement call), "info" (context for the reader).
    """

    checker: str
    code: str
    severity: str
    message: str
    detail: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")


@dataclasses.dataclass
class AuditReport:
    """Outcome of auditing one jitted program."""

    label: str
    findings: list[Finding] = dataclasses.field(default_factory=list)
    # Checker-populated context (collective counts, donation stats, dot
    # dtype histogram, ...) for JSON output and tables.
    summary: dict = dataclasses.field(default_factory=dict)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "warn"]

    def clean(self, *, allow_warnings: bool = True) -> bool:
        """True when the program passed: no errors (and, with
        allow_warnings=False, no warnings either)."""
        if self.errors:
            return False
        return allow_warnings or not self.warnings

    def extend(self, findings: list[Finding]) -> None:
        self.findings.extend(findings)

    def to_json(self) -> dict[str, Any]:
        return {
            "label": self.label,
            "clean": self.clean(),
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "summary": self.summary,
        }

    def table(self) -> str:
        """Human-readable report block."""
        status = "PASS" if self.clean() else "FAIL"
        lines = [f"=== audit: {self.label} [{status}] ==="]
        cc = self.summary.get("collective_counts")
        if cc is not None:
            pretty = (
                ", ".join(f"{k}x{v}" for k, v in sorted(cc.items()))
                or "(none)"
            )
            lines.append(f"  collectives: {pretty}")
        don = self.summary.get("donation")
        if don:
            lines.append(
                "  donation:    {aliased}/{expected} state buffers aliased"
                .format(**don)
            )
        mem = self.summary.get("memory")
        if mem is not None:
            lines.append(
                "  memory:      peak {} live ({} saved by aliasing), "
                "{} donated / {} unaliased".format(
                    _fmt_bytes(mem.get("peak_live_bytes", 0)),
                    _fmt_bytes(mem.get("alias_saved_bytes", 0)),
                    _fmt_bytes(mem.get("donated_bytes", 0)),
                    _fmt_bytes(mem.get("unaliased_donated_bytes", 0)),
                )
            )
        cost = self.summary.get("cost")
        if cost is not None:
            roof = cost.get("roofline") or {}
            lines.append(
                "  cost:        {:,} flops, {} moved, {} on wire "
                "(AI {:.2f}, {}-bound{})".format(
                    cost.get("flops", 0),
                    _fmt_bytes(cost.get("hbm_bytes", 0)),
                    _fmt_bytes(cost.get("wire_bytes", 0)),
                    cost.get("arithmetic_intensity", 0.0),
                    roof.get("bound", "?"),
                    ", LOWER BOUND" if cost.get("lower_bound") else "",
                )
            )
        dots = self.summary.get("dot_dtypes")
        if dots:
            pretty = ", ".join(f"{k}x{v}" for k, v in sorted(dots.items()))
            lines.append(f"  dot dtypes:  {pretty}")
        haz = self.summary.get("hazards")
        if haz is not None:
            lines.append(
                f"  hazards:     {haz.get('callbacks', 0)} callback(s), "
                f"{haz.get('weak_type_inputs', 0)} weak-typed input(s), "
                f"{haz.get('chained_converts', 0)} chained convert(s)"
            )
        vma = self.summary.get("vma")
        if vma is not None:
            lines.append(
                f"  vma:         {vma.get('shard_map_bodies', 0)} "
                f"shard_map body(ies), {vma.get('outputs_checked', 0)} "
                "output(s) checked"
            )
        for f in self.findings:
            if f.severity == "info":
                continue
            lines.append(f"  [{f.severity.upper():5s}] {f.code}: {f.message}")
        return "\n".join(lines)


def reports_to_json(reports: list[AuditReport]) -> str:
    return json.dumps(
        {
            "clean": all(r.clean() for r in reports),
            "reports": [r.to_json() for r in reports],
        },
        indent=2,
        sort_keys=True,
    )
