"""Typed configuration for model / data / training / mesh.

The reference has no config system — hyperparameters are hardcoded constants in
each entry script (reference train_baseline.py:24-31, train_ddp.py:59-64,
train_fsdp.py:98-103) and model shape comes from HF AutoConfig
(train_baseline.py:24). We replace that with small frozen dataclasses
(SURVEY.md §5.6): enough structure to be testable, no Hydra-scale machinery.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class ModelConfig:
    """Transformer architecture config.

    Field names follow GPT-2 conventions (reference model/my_gpt2.py uses the
    HF GPT2Config fields n_embd/n_head/n_layer/n_ctx, vocab_size,
    activation_function, layer_norm_epsilon, *_pdrop).
    """

    # Family: "gpt2" (learned positions, LayerNorm, gelu MLP, tied head) or
    # "llama" (RoPE, RMSNorm, SwiGLU, untied head) — SURVEY.md §7 stage 8 /
    # BASELINE.md configs 4-5.
    family: str = "gpt2"

    vocab_size: int = 50257
    n_ctx: int = 1024  # max sequence length (positional table size for gpt2)
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    # Defaults to n_head (no GQA); llama-family configs may set fewer KV heads.
    n_kv_head: int | None = None
    # MLP hidden size; None → 4*n_embd (gpt2) or the llama 8/3 rule rounded.
    n_inner: int | None = None

    activation_function: str = "gelu_new"
    layer_norm_epsilon: float = 1e-5
    # RoPE base frequency (llama family only).
    rope_theta: float = 10000.0

    # Dropout probabilities (reference my_gpt2.py:25-26,152 — attn, resid, embd).
    embd_pdrop: float = 0.1
    attn_pdrop: float = 0.1
    resid_pdrop: float = 0.1
    # Attention dropout under explicit tensor parallelism: "reject" (default
    # — attn_pdrop > 0 with a tensor axis fails at build time, preserving
    # the bitwise single-device parity contract) or "folded" (opt-in: each
    # tensor shard folds its axis index into the attention-dropout key, so
    # its local heads draw INDEPENDENT masks — statistically equivalent to
    # the single-device draw, NOT bitwise-identical; embd/resid dropout
    # keys stay replicated so non-attention activations remain
    # bitwise-replicated across shards).
    tensor_dropout: str = "reject"

    # Numerics: params kept in param_dtype, activations computed in dtype.
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # Dtype the LM head emits. float32 matches the reference's fp32 logits;
    # "bfloat16" halves the [B, T, V] HBM traffic through the head + loss
    # (the MXU still accumulates in f32; cross-entropy upcasts to f32).
    logits_dtype: str = "float32"

    # Fuse the LM-head matmul into the cross-entropy loss
    # (ops/losses.linear_cross_entropy): logits are produced and consumed in
    # vocab blocks, so the [B, T, V] logits tensor never exists — the
    # largest activation in the step (823 MB bf16 at GPT-2 bench shapes,
    # 2.1 GB at llama-3 vocabulary). Honored by EVERY training path:
    # trainer/pjit, explicit (shard_map), and pipeline (the fusion lands on
    # the last stage, which owns the head). apply() itself still returns
    # logits unless called with return_hidden=True.
    fused_head_ce: bool = False

    # Selective activation checkpointing per block (reference my_gpt2.py:145,
    # 175-183 + pytorch_utils.py:5-17): save compute-intensive matmul outputs,
    # recompute the rest. One of: "none", "full", "dots", "dots_no_batch",
    # "names" (recommended: saves the tagged projection outputs and the
    # flash kernel's o/l/m, but never the quadratic score matrix), or
    # "flash" (ONLY the flash o/l/m — the long-context policy for
    # regimes where per-layer projection saves OOM HBM; see ops/remat.py).
    remat: str = "dots"
    # Unroll factor for the scan-over-layers (1 = no unroll). Unrolling
    # lets XLA fuse/pipeline across layer boundaries (e.g. merge adjacent
    # activation-save dynamic-update-slices) at the cost of HLO size.
    scan_unroll: int = 1

    # Attention implementation: "naive" (materialises the T×T score matrix like
    # reference my_gpt2.py:60-77) or "flash" (blockwise online-softmax /
    # Pallas). Whether the sequence IS sharded is a parallelism-layer
    # concern (parallel/); seq_impl picks the context-parallel technique
    # when it is: "ring" (ppermute KV ring, works for any head count) or
    # "ulysses" (head/sequence all-to-all, needs seq | n_head and
    # seq | kv_heads).
    attention_impl: str = "naive"
    seq_impl: str = "ring"

    # Mixture-of-Experts (ops/moe.py): 0 = dense MLP (reference behavior);
    # >0 replaces each block's MLP with n_experts expert MLPs and a top-1
    # router — dense-style experts for gpt2, SwiGLU experts for llama.
    # Aux-loss coefficient weights the Switch load-balancing term added to
    # the training objective.
    n_experts: int = 0
    expert_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    # Router top-k: 1 = Switch (argmax expert, raw-prob gate); k>1 =
    # GShard-style (k best experts, renormalised gates).
    moe_top_k: int = 1
    # Token dispatch: "einsum" (one-hot [A,X,C] tensor — exact-parity
    # path), "sort" (sort/segment path, O(A·D) memory — the at-scale
    # form), "auto" picks by dispatch-tensor size (ops/moe.py).
    moe_dispatch: str = "auto"

    def __post_init__(self) -> None:
        if self.n_embd % self.n_head != 0:
            raise ValueError(
                f"n_embd={self.n_embd} not divisible by n_head={self.n_head}"
            )
        if self.family not in ("gpt2", "llama"):
            raise ValueError(f"unknown model family: {self.family!r}")
        # Ring attention is selected by the parallelism layer (seq_axis in
        # ops/attention.py), not by this per-model switch.
        if self.attention_impl not in ("naive", "flash"):
            raise ValueError(
                f"unknown attention_impl: {self.attention_impl!r} "
                "(implemented: naive, flash)"
            )
        if self.seq_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown seq_impl: {self.seq_impl!r} "
                "(implemented: ring, ulysses)"
            )
        if self.n_experts and self.family not in ("gpt2", "llama"):
            raise ValueError(
                "MoE (n_experts > 0) requires the gpt2 or llama family"
            )
        if self.n_experts and not (1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} out of range for "
                f"n_experts={self.n_experts}"
            )
        if self.moe_dispatch not in ("auto", "einsum", "sort"):
            raise ValueError(
                f"unknown moe_dispatch: {self.moe_dispatch!r} "
                "(implemented: auto, einsum, sort)"
            )
        if self.scan_unroll < 1:
            raise ValueError(
                f"scan_unroll must be >= 1, got {self.scan_unroll}"
            )
        if self.tensor_dropout not in ("reject", "folded"):
            raise ValueError(
                f"unknown tensor_dropout: {self.tensor_dropout!r} "
                "(implemented: reject, folded)"
            )

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head if self.n_kv_head is not None else self.n_head

    @property
    def inner_dim(self) -> int:
        if self.n_inner is not None:
            return self.n_inner
        if self.family == "llama":
            # Llama FFN rule: 2/3 * 4d, rounded up to a multiple of 256.
            return ((8 * self.n_embd // 3) + 255) // 256 * 256
        return 4 * self.n_embd

    def replace(self, **kw: Any) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


# Preset shapes. gpt2/gpt2-medium/large/xl match HF AutoConfig presets the
# reference pulls (train_baseline.py:24 uses "gpt2-large", memory_analysis.py
# uses "gpt2"). gpt2-1p3b is the BASELINE.md config-3 size (GPT-3 XL shape).
_GPT2_PRESETS: dict[str, dict[str, int]] = {
    "gpt2": dict(n_embd=768, n_layer=12, n_head=12),  # 124M
    "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),  # 355M
    "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),  # 774M
    "gpt2-xl": dict(n_embd=1600, n_layer=48, n_head=25),  # 1.56B
    "gpt2-1p3b": dict(n_embd=2048, n_layer=24, n_head=16),  # 1.31B
    # Smoke-test shape for CPU runs and CLI examples.
    "tiny": dict(
        vocab_size=256, n_ctx=128, n_embd=64, n_layer=2, n_head=4,
        dtype="float32",
    ),
}

_LLAMA_PRESETS: dict[str, dict[str, Any]] = {
    # Llama-3.2-1B / Llama-3.1-8B shapes (BASELINE.md configs 4-5).
    "llama3-1b": dict(
        vocab_size=128256, n_ctx=8192, n_embd=2048, n_layer=16, n_head=32,
        n_kv_head=8, n_inner=8192, rope_theta=500000.0,
    ),
    "llama3-8b": dict(
        vocab_size=128256, n_ctx=8192, n_embd=4096, n_layer=32, n_head=32,
        n_kv_head=8, n_inner=14336, rope_theta=500000.0,
    ),
}


def model_config(name: str, **overrides: Any) -> ModelConfig:
    """Look up a preset by name (the TPU-native analogue of
    ``AutoConfig.from_pretrained`` in reference train_baseline.py:24)."""
    if name in _GPT2_PRESETS:
        base: dict[str, Any] = dict(family="gpt2", **_GPT2_PRESETS[name])
    elif name in _LLAMA_PRESETS:
        base = dict(
            family="llama",
            activation_function="silu",
            layer_norm_epsilon=1e-5,
            embd_pdrop=0.0,
            attn_pdrop=0.0,
            resid_pdrop=0.0,
            **_LLAMA_PRESETS[name],
        )
    else:
        raise KeyError(
            f"unknown model preset {name!r}; known: "
            f"{sorted(_GPT2_PRESETS) + sorted(_LLAMA_PRESETS)}"
        )
    base.update(overrides)
    return ModelConfig(**base)


@dataclass(frozen=True)
class DataConfig:
    """Data pipeline config (reference data/data_loader.py defaults)."""

    data_dir: str = ".cache/data/fineweb10B"
    batch_size: int = 8  # per-process micro-batch B (reference :83)
    seq_len: int = 1024  # T (reference :84)
    num_train_files: int = 10  # reference train_baseline.py:50
    source: str = "fineweb10B"  # or "synthetic" for tests / zero-egress runs
    synthetic_tokens: int = 2_000_000
    seed: int = 42


@dataclass(frozen=True)
class TrainConfig:
    """Training-loop config (reference train_baseline.py:26-31,61-64 and
    train/trainer.py:9-47)."""

    global_batch_size: int = 32
    micro_batch_size: int = 8
    num_steps: int = 20
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    grad_clip_norm: float | None = None
    # Exclude rank<2 params (norm scales, biases) from weight decay — the
    # modern pretraining convention. Default OFF: the reference decays
    # every param (torch AdamW default, train_baseline.py:61).
    decay_exclude_1d: bool = False
    # Gradient-accumulation buffer dtype (A > 1 only; honoured by the
    # single-device, pjit and explicit paths — the pipeline path's
    # accumulation dtype follows AD). "float32" (default) is the safe
    # convention; "bfloat16" halves the accumulator HBM — the buffer that
    # decides whether a 774M model accumulates on one 16 GB chip at all
    # (see scripts/_common.py --param-dtype help). bf16 accumulation
    # loses ~8 mantissa bits across the A partial sums; acceptable at
    # small A, measure before using at large A.
    accum_dtype: str = "float32"
    # Cosine anneal to min_lr_ratio * learning_rate over num_steps
    # (reference train_baseline.py:62-64: CosineAnnealingLR eta_min=0.1*lr).
    lr_schedule: str = "cosine"
    min_lr_ratio: float = 0.1
    warmup_steps: int = 0

    seed: int = 42
    log_every_n_steps: int = 10
    save_every_n_steps: int | None = None
    checkpoint_dir: str = "checkpoints"
    # Retain only the newest N checkpoints (None = keep all, the
    # reference's behavior). Pruning runs on process 0 after each
    # successful save. Validated at construction (grad_accum_steps-style
    # late failures would kill a run at its first save).
    keep_checkpoints: int | None = None
    # Overlap checkpoint writes with training (orbax AsyncCheckpointer):
    # the device arrays are snapshotted at the save step, serialization
    # runs in background threads, and the checkpoint becomes visible at
    # the next save / end of training (train/checkpoint.py
    # save_checkpoint_async). Off = the reference's blocking-save model.
    async_checkpoint: bool = False

    def __post_init__(self) -> None:
        if self.keep_checkpoints is not None and self.keep_checkpoints < 1:
            raise ValueError(
                f"keep_checkpoints must be >= 1 or None, got "
                f"{self.keep_checkpoints}"
            )
        if self.accum_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown accum_dtype: {self.accum_dtype!r} "
                "(implemented: float32, bfloat16)"
            )
        if self.anomaly_guard:
            # Late guard failures would kill a run at its first anomaly;
            # validate the policy here (GuardConfig re-validates the
            # traced parameters).
            from pytorch_distributed_tpu.train.guard import GuardConfig

            GuardConfig(
                spike_factor=self.guard_spike_factor,
                ema_decay=self.guard_ema_decay,
                warmup_steps=self.guard_warmup_steps,
                rollback_after=self.guard_rollback_after,
            )
            if self.guard_max_rollbacks < 1:
                raise ValueError(
                    f"guard_max_rollbacks must be >= 1, got "
                    f"{self.guard_max_rollbacks}"
                )
    # Traced anomaly guard (train/guard.py): a non-finite loss/grad
    # sentinel + EMA loss-spike check + corrupt-token-id check computed
    # INSIDE the compiled step. On anomaly the update is a traced no-op
    # (params/opt_state carried unchanged) and counters ride
    # TrainState.guard — zero host syncs per step, zero recompiles. The
    # host reads the counters at the existing log-window sync; after
    # guard_rollback_after CONSECUTIVE anomalies it rolls back to the
    # last good checkpoint (see docs/ROBUSTNESS.md §9).
    anomaly_guard: bool = False
    guard_spike_factor: float = 3.0
    guard_ema_decay: float = 0.98
    guard_warmup_steps: int = 10
    # Consecutive anomalies before the host rolls back (None: skip-only —
    # anomalous updates are dropped but training never rewinds).
    guard_rollback_after: int | None = 3
    # Hard bound on rollbacks per train() call: a persistently anomalous
    # run fails loudly instead of thrashing forever.
    guard_max_rollbacks: int = 8
    # On rollback, do NOT rewind the data stream: the window between the
    # last checkpoint and the rollback is dropped (the policy for
    # PERSISTENT data corruption — deterministic replay would hit the
    # same bad batches again). Off (default): replay the window, the
    # right call for transient faults (bit-identical recovery).
    guard_skip_window: bool = False
    # Optional JSONL metrics sink: every logged window (step/loss/lr/
    # elapsed) is appended as one JSON object — machine-readable run
    # history beyond the reference's stdout prints (process 0 only under
    # the distributed trainer).
    metrics_path: str | None = None
    # Graceful preemption (TPU pods get reclaimed): on SIGTERM/SIGINT the
    # train loop finishes the in-flight step, writes a checkpoint (with
    # the data-stream position), and returns — so --resume continues the
    # run exactly. Opt-in; recovery story beyond the reference's plain
    # checkpoint cadence (SURVEY.md §5.3).
    save_on_preemption: bool = False
    # Multi-host: how often (in optimizer steps) processes agree on a stop
    # decision. Each sync is a host-blocking process_allgather; 1 = every
    # step (tightest preemption response), N amortises the sync cost at
    # the price of up to N-1 extra steps after the signal. Signals landing
    # between syncs are deferred to the next sync so every process reaches
    # the same decision at the same step. Multi-host preemption requires
    # lockstep loaders (DistributedTokenShardLoader): all processes must
    # exhaust data at the same iteration or ANY collective — including the
    # train step itself — deadlocks.
    preemption_sync_every_n_steps: int = 1

    def grad_accum_steps(self, data_parallel_size: int = 1) -> int:
        """Micro-batches per optimizer step. Single-device rule
        (reference train/trainer.py:31-34) and the distributed rule
        global // (micro * world) (reference train/distributed_trainer.py:84-88)."""
        denom = self.micro_batch_size * data_parallel_size
        if self.global_batch_size % denom != 0:
            raise ValueError(
                f"global_batch_size={self.global_batch_size} must be divisible "
                f"by micro_batch_size*dp={denom}"
            )
        return self.global_batch_size // denom


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh / parallelism config (SURVEY.md §2.2, §5.8).

    Axes follow the scaling-book convention: data (DP replicas), fsdp
    (parameter/grad/opt-state sharding), tensor (TP), seq (sequence/context
    parallelism for ring attention), pipe (pipeline stages — GPipe-style
    layer partitioning, parallel/pipeline.py). Sizes of 1 collapse the axis.
    """

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1
    # Expert parallelism (MoE): expert weights shard over this axis and the
    # batch shards over it too (it is a data axis for non-expert params);
    # all_to_all moves token slots to their expert's owner (ops/moe.py).
    expert: int = 1

    # FSDP sharding strategy, mirroring reference train_fsdp.py:49-59
    # (plus the ZeRO-1 level torch FSDP lacks):
    #   "full_shard"     — params+grads+opt sharded (ZeRO-3)
    #   "shard_grad_op"  — grads+opt sharded, params replicated (ZeRO-2)
    #   "shard_opt"      — opt sharded only; grads all-reduced replicated,
    #                      each shard updates its slice, updated params
    #                      re-gathered (ZeRO-1)
    #   "no_shard"       — DDP-equivalent
    strategy: str = "full_shard"

    # Pipeline schedule (pipe > 1): "gpipe" (backward by AD transposition)
    # or "1f1b" (hand-scheduled PipeDream-flush — activation stash bounded
    # at pipe slots instead of the microbatch count; parallel/pipeline.py).
    pipe_schedule: str = "gpipe"

    # Latency-hiding schedule knobs for the explicit (shard_map) path
    # (parallel/explicit.py; ops/layer_scan.py):
    #
    # prefetch_buffers (ZeRO-3/full_shard only): how many EXTRA layers'
    # params may be in flight beyond the one being computed. 0 = the
    # just-in-time schedule (gather layer l inside layer l's scan body —
    # compute stalls on every gather). N > 0 restructures the layer scan
    # into windows of N+1 layers whose all_gathers are all issued before
    # the window's first block runs, so layer l+1's gather overlaps layer
    # l's compute (and the rematted backward re-gathers a whole window up
    # front the same way, letting the AD-transposed reduce-scatters
    # interleave with the remaining backward compute). SOFT hint: the
    # effective window is the largest divisor of n_layer <= N+1. Costs
    # N extra layers' worth of live gathered params in HBM.
    prefetch_buffers: int = 0
    # rs_buckets (ZeRO-2/shard_grad_op only): when > 0, the boundary
    # per-leaf gradient psum_scatters are coalesced into ~rs_buckets
    # bucketed collectives (flattened + concatenated per dtype/vma group,
    # parallel/zero.scatter_grads_bucketed) — fewer, larger transfers
    # that amortise per-collective latency and let XLA pipeline buckets
    # against each other. 0 = per-leaf scatters (the teaching layout).
    rs_buckets: int = 0

    axis_order: tuple[str, ...] = (
        "pipe", "data", "fsdp", "expert", "seq", "tensor"
    )

    # Device subset for this mesh: the process-local ``jax.devices()``
    # ids this mesh builds over, in mesh order. None keeps the historic
    # behaviour (first ``num_devices`` of ``jax.devices()``). This is
    # how a serving fleet pins each replica to its OWN slice of the
    # machine (e.g. 4 replicas x TP=2 over 8 devices) instead of every
    # replica time-slicing device 0 — the mesh is otherwise identical,
    # so programs, shardings, and pinned collective budgets are
    # untouched by placement.
    device_ids: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        if self.device_ids is not None:
            ids = tuple(int(d) for d in self.device_ids)
            object.__setattr__(self, "device_ids", ids)
            if len(set(ids)) != len(ids):
                raise ValueError(
                    f"device_ids must be unique, got {ids}"
                )
            if len(ids) != self.num_devices:
                raise ValueError(
                    f"device_ids has {len(ids)} entries but the mesh "
                    f"needs {self.num_devices} devices"
                )
        if self.strategy not in (
            "full_shard", "shard_grad_op", "shard_opt", "no_shard"
        ):
            raise ValueError(f"unknown FSDP strategy: {self.strategy!r}")
        if self.pipe_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"unknown pipe_schedule: {self.pipe_schedule!r} "
                "(implemented: gpipe, 1f1b)"
            )
        if self.prefetch_buffers < 0:
            raise ValueError(
                f"prefetch_buffers must be >= 0, got {self.prefetch_buffers}"
            )
        if self.rs_buckets < 0:
            raise ValueError(
                f"rs_buckets must be >= 0, got {self.rs_buckets}"
            )

    @property
    def num_devices(self) -> int:
        return (
            self.data * self.fsdp * self.tensor * self.seq * self.pipe
            * self.expert
        )

    @property
    def shape(self) -> dict[str, int]:
        return {ax: getattr(self, ax) for ax in self.axis_order}


@dataclass(frozen=True)
class ExperimentConfig:
    """Bundle of everything an entry point needs."""

    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
