"""Seeded serving workloads: ONE arrival-stream generator for every
consumer.

Before this module, three near-copies of "seeded Poisson-ish mixed
traffic" lived in ``scripts/soak.py`` and the ``decode_bench``
serving legs — and they had already drifted on the details that decide
whether two runs are comparable: one drew a per-request key as
``jax.random.key(base + i)``, another as ``fold_in(key(base), i)``, a
third shared ONE key across every sampled request. A robustness claim
("DONE outputs bit-equal to a fault-free run of the same schedule") is
only meaningful when "the same schedule" is a single function of the
seed, so the generator lives here and the soak, the bench legs, the
router load generator, and the tests all consume it.

Conventions (the points the copies drifted on, now pinned):

- **Per-request keys** are ``fold_in(jax.random.key(key_seed), i)`` —
  one base key, folded by request index. Requests are independent
  streams whatever engine or replica serves them.
- **Sampling configs** cycle through ``sampling_cycle`` by request
  index (greedy rows share batches with sampled ones by default).
- **Arrivals** are exponential inter-arrival times (Poisson process)
  from the SAME generator that drew the requests, so one seed fixes
  offered load and content together.

Everything returns plain host data (numpy arrays + ``submit`` kwarg
dicts); nothing here touches a device.
"""

from __future__ import annotations

import numpy as np

# Greedy rows deliberately share the stream with sampled ones: the
# batched engines' per-row traced sampling state is exactly what makes
# that free, and a workload without the mix would under-exercise it.
DEFAULT_SAMPLING_CYCLE = (
    dict(temperature=0.8, top_k=20),
    dict(temperature=1.0, top_p=0.9),
    dict(),  # greedy
)


def request_stream(
    rng: np.random.Generator,
    *,
    n: int,
    vocab_size: int,
    prompt_len: tuple[int, int],
    max_new: int | tuple[int, int],
    sampling_cycle=DEFAULT_SAMPLING_CYCLE,
    key_seed: int | None = None,
    shared_prefix: np.ndarray | None = None,
    p_deadline: float = 0.0,
    deadline_range: tuple[float, float] = (0.5, 4.0),
) -> list[dict]:
    """The seeded request schedule: a list of ``engine.submit`` /
    ``router.submit`` kwarg dicts (prompt, max_new_tokens, sampling
    config, per-request key, optional ``timeout_s`` deadline).

    ``prompt_len`` draws uniformly over [lo, hi] inclusive (the random
    TAIL length when ``shared_prefix`` is given — the prefix-cache
    traffic shape); ``max_new`` is fixed or a [lo, hi] draw;
    ``p_deadline`` attaches a ``timeout_s`` drawn from
    ``deadline_range`` to that fraction of requests (engine-clock
    seconds — drive with a VirtualClock to make expiries replayable).
    ``key_seed`` defaults to a draw from ``rng`` so the whole stream
    stays a pure function of the caller's seed either way."""
    import jax

    if key_seed is None:
        key_seed = int(rng.integers(0, 2**31 - 1))
    base_key = None  # built lazily: greedy-only streams never need jax
    lo, hi = prompt_len
    reqs: list[dict] = []
    for i in range(n):
        tp = int(rng.integers(lo, hi + 1))
        tail = rng.integers(0, vocab_size, (tp,)).astype(np.int32)
        prompt = (
            tail if shared_prefix is None
            else np.concatenate([np.asarray(shared_prefix, np.int32), tail])
        )
        mn = (
            int(max_new) if isinstance(max_new, int)
            else int(rng.integers(max_new[0], max_new[1] + 1))
        )
        kw = dict(sampling_cycle[i % len(sampling_cycle)])
        if kw.get("temperature"):
            if base_key is None:
                base_key = jax.random.key(key_seed)
            kw["key"] = jax.random.fold_in(base_key, i)
        # The deadline Bernoulli draws UNCONDITIONALLY so the request
        # content downstream of request i is identical whether or not
        # this stream uses deadlines — legs with and without them stay
        # comparable request-for-request.
        u, d = rng.random(), float(rng.uniform(*deadline_range))
        if u < p_deadline:
            kw["timeout_s"] = d
        reqs.append(dict(prompt=prompt, max_new_tokens=mn, **kw))
    return reqs


def repetitive_request_stream(
    rng: np.random.Generator,
    *,
    n: int,
    vocab_size: int,
    pattern_len: tuple[int, int] = (2, 5),
    repeats: tuple[int, int] = (3, 6),
    max_new: int | tuple[int, int] = 16,
) -> list[dict]:
    """Seeded SELF-REPETITIVE greedy traffic — the stream speculative
    decoding exists for (code, extraction, quote-heavy summarisation):
    each prompt is a per-request random pattern tiled ``repeats``
    times, so the prompt-lookup n-gram match fires from the first
    generated token, and greedy decode of a fixed model self-loops
    shortly after, keeping it firing. All rows are greedy by
    construction (the engines draft only greedy rows); the LOW-
    repetition counterpart is an ordinary sampled ``request_stream``
    (sampled rows ride zero-draft lanes and pay the verify width for
    nothing — the regression bound the spec bench documents)."""
    lo, hi = pattern_len
    reqs: list[dict] = []
    for _ in range(n):
        pat = rng.integers(
            0, vocab_size, (int(rng.integers(lo, hi + 1)),)
        ).astype(np.int32)
        prompt = np.tile(pat, int(rng.integers(repeats[0], repeats[1] + 1)))
        mn = (
            int(max_new) if isinstance(max_new, int)
            else int(rng.integers(max_new[0], max_new[1] + 1))
        )
        reqs.append(dict(prompt=prompt, max_new_tokens=mn))
    return reqs


def tiered_stream(
    seed: int,
    *,
    vocab_size: int,
    tiers: dict[str, dict],
) -> list[dict]:
    """Mixed-SLO arrival stream: ``tiers`` maps a priority class name
    (serving/scheduler.py) -> ``request_stream`` kwargs (``n``,
    ``prompt_len``, ``max_new``, ...). Entries carry ``priority=`` and
    interleave proportionally by index, so one submit loop drives the
    whole mix and every scheduler batch window sees all tiers.

    Each tier's content derives from ``(seed, tier name)`` ALONE —
    adding or dropping a tier never changes another tier's prompts,
    keys, or sampling draws. That independence is what makes the
    scenarios bench's "interactive p99 loaded vs unloaded" a
    request-for-request comparison: the unloaded leg replays the
    interactive tier's EXACT requests without the batch flood."""
    import zlib

    from pytorch_distributed_tpu.serving.scheduler import check_priority

    tagged: list[tuple[float, int, int, dict]] = []
    for tier, kw in tiers.items():
        check_priority(tier)
        # Stable per-tier substream: crc32(tier) + seed, untouched by
        # the other tiers (a shared parent rng would re-order draws).
        sub = np.random.default_rng([zlib.crc32(tier.encode()), seed])
        reqs = request_stream(sub, vocab_size=vocab_size, **kw)
        for i, r in enumerate(reqs):
            r["priority"] = tier
            # Fractional position in the tier -> global interleave
            # order; rank-then-index tiebreak keeps it deterministic.
            tagged.append(
                ((i + 0.5) / len(reqs), check_priority(tier), i, r)
            )
    return [r for *_, r in sorted(tagged, key=lambda e: e[:3])]


def disagg_stream(
    seed: int,
    *,
    n: int,
    vocab_size: int,
    p_heavy_prefill: float = 0.5,
    heavy_prompt_len: tuple[int, int] = (96, 160),
    heavy_max_new: tuple[int, int] = (4, 8),
    light_prompt_len: tuple[int, int] = (8, 24),
    light_max_new: tuple[int, int] = (24, 48),
    sampling_cycle=DEFAULT_SAMPLING_CYCLE,
) -> list[dict]:
    """The disaggregation workload: a seeded mix of the two shapes
    whose INTERFERENCE prefill/decode separation exists to remove —
    ``heavy_prefill`` rows (long prompt, short decode: the chunked
    prefill that stalls a colocated engine's decode ticks) and
    ``light`` rows (short prompt, long decode: the interactive traffic
    whose inter-token p99 that stall inflates). Each dict is a
    ``submit`` kwarg set plus a ``"kind"`` tag ("heavy_prefill" /
    "light") the driver pops before submitting — the bench classifies
    its latency percentiles by it.

    Request ``i``'s content (class draw, lengths, tokens, deadline-free
    sampling config) derives from ``(seed, i)`` ALONE — its own
    ``default_rng([crc32("disagg"), seed, i])`` substream plus the
    ``fold_in(key(seed), i)`` sampling key — so truncating, extending,
    or re-partitioning the stream never perturbs any other request:
    colocated and disaggregated legs replay request-for-request
    identical content whatever fleet serves them."""
    import zlib

    import jax

    base_key = None
    reqs: list[dict] = []
    for i in range(n):
        sub = np.random.default_rng([zlib.crc32(b"disagg"), seed, i])
        heavy = bool(sub.random() < p_heavy_prefill)
        lo, hi = heavy_prompt_len if heavy else light_prompt_len
        tp = int(sub.integers(lo, hi + 1))
        prompt = sub.integers(0, vocab_size, (tp,)).astype(np.int32)
        mlo, mhi = heavy_max_new if heavy else light_max_new
        mn = int(sub.integers(mlo, mhi + 1))
        kw = dict(sampling_cycle[i % len(sampling_cycle)])
        if kw.get("temperature"):
            if base_key is None:
                base_key = jax.random.key(seed)
            kw["key"] = jax.random.fold_in(base_key, i)
        reqs.append(dict(
            kind="heavy_prefill" if heavy else "light",
            prompt=prompt, max_new_tokens=mn, **kw,
        ))
    return reqs


def session_stream(
    rng: np.random.Generator,
    *,
    n_sessions: int,
    turns: int,
    vocab_size: int,
    open_len: tuple[int, int],
    turn_len: tuple[int, int],
    max_new: int | tuple[int, int],
    sampling_cycle=DEFAULT_SAMPLING_CYCLE,
    key_seed: int | None = None,
) -> list[list[dict]]:
    """The seeded multi-turn chat schedule: ``n_sessions`` scripts of
    ``turns`` turn dicts each. A turn dict is ``{"tail": [t] int32
    tokens, "max_new_tokens": n, <sampling kwargs>}`` — the driver
    (bench leg, soak, tests) submits ``concat(recorded transcript,
    tail)`` as the turn's prompt, which is exactly the
    conversation-so-far-plus-new-message shape ``submit(session=)``
    validates. Turn 1's tail draws ``open_len`` tokens, later turns
    draw ``turn_len``; per-turn keys are
    ``fold_in(key(key_seed), session * turns + turn)`` (the PR-11
    fold_in discipline, one base key for the whole schedule)."""
    import jax

    if key_seed is None:
        key_seed = int(rng.integers(0, 2**31 - 1))
    base_key = None
    sessions: list[list[dict]] = []
    for s in range(n_sessions):
        script: list[dict] = []
        for t in range(turns):
            lo, hi = open_len if t == 0 else turn_len
            tail = rng.integers(
                0, vocab_size, (int(rng.integers(lo, hi + 1)),)
            ).astype(np.int32)
            mn = (
                int(max_new) if isinstance(max_new, int)
                else int(rng.integers(max_new[0], max_new[1] + 1))
            )
            kw = dict(sampling_cycle[(s * turns + t) % len(sampling_cycle)])
            if kw.get("temperature"):
                if base_key is None:
                    base_key = jax.random.key(key_seed)
                kw["key"] = jax.random.fold_in(base_key, s * turns + t)
            script.append(dict(tail=tail, max_new_tokens=mn, **kw))
        sessions.append(script)
    return sessions


def exponential_arrivals(
    rng: np.random.Generator, n: int, mean_interarrival_s: float,
    start: float = 0.0,
) -> np.ndarray:
    """Arrival timestamps of a Poisson process: the first request lands
    at ``start``, the rest follow exponential inter-arrival gaps. Every
    serving bench leg calibrates ``mean_interarrival_s`` against a
    measured service rate and then replays ONE schedule through every
    leg under comparison."""
    if n < 1:
        return np.zeros((0,))
    gaps = rng.exponential(mean_interarrival_s, n - 1)
    return start + np.concatenate([[0.0], np.cumsum(gaps)])


def tick_bursts(
    rng: np.random.Generator, max_per_tick: int, length: int = 997
) -> list[int]:
    """Seeded per-tick arrival burst sizes (0..max_per_tick inclusive)
    for tick-driven drivers (the soak): bursty, seed-reproducible churn
    without a wall clock. A long prime-length cycle avoids resonating
    with the scheduler's own periodicities."""
    return [int(rng.integers(0, max_per_tick + 1)) for _ in range(length)]
