"""Multi-tenant LoRA adapters for the batched serving engines.

N tenants fine-tune one base model with low-rank deltas; serving them
as N engines would cost N param trees, N KV pools, and N compiled
program sets. Instead the adapters ride the EXISTING programs as
TRACED per-row terms: every dispatch takes (a) one stacked adapter
tree — ``[L, slots, ...]`` low-rank factors with tenant slot 0
permanently the ZERO adapter — and (b) a ``[B]`` int32 tenant-slot
vector, and ``models/decode.forward`` applies each row's delta as a
per-row ``(B, r)·(r, D)`` pair of einsums next to the base projection
(``decode.lora_delta``). Consequences, all machine-checked:

- **Zero extra compiles**: the stacked tree is preallocated at
  ``max_tenants + 1`` slots, so registering a tenant changes operand
  VALUES, never shapes — N tenants share the warmed compile set
  (registry cases ``decode_paged_*_lora`` pin it, and the churn test
  asserts ``compile_count`` flat across registrations).
- **Zero extra caches, and the prefix cache stays tenant-agnostic**:
  the target set deliberately never touches a K or V projection
  (query + attention-output only), so a cache position's K/V remains a
  pure function of the TOKENS alone — two tenants sharing a system
  prompt share its pages, and prefix-cache keys need no tenant salt.
  An adapter on wk/wv would silently poison cross-tenant sharing;
  extending the target set there means folding the tenant slot into
  the block-pool chain keys first.
- **Per-tenant isolation**: row b's delta reads only
  ``stack[tenants[b]]`` — a gather, no cross-row term — so the PR-5
  neighbour-independence pin extends per tenant: a tenant's rows in a
  mixed batch are bit-equal the same requests on an engine serving that
  tenant alone, and slot-0 rows are bit-equal the adapter-less base
  engine (adding an exact-zero delta is exact).
- **TP composes**: column-parallel targets (q) shard the B factor's
  output axis with the base weight; row-parallel targets (``c_proj`` /
  ``wo``) shard the A factor's contracting dim instead, and the delta
  joins the base PARTIAL before the existing Megatron psum — linearity
  makes the reduction shared, so the pinned all-reduce=2 survives
  (``decode_batched_step_tp_lora`` in the audit registry).

Targets (classic LoRA attention placement, K/V excluded by design):
- gpt2: ``q`` (the query third of the fused c_attn output) and
  ``c_proj`` (attention output).
- llama: ``wq`` and ``wo``.

Registration is host-side and rare; ``device_tree()`` memoizes the
device upload per registry ``version``.
"""

from __future__ import annotations

import numpy as np

from pytorch_distributed_tpu.config import ModelConfig
from pytorch_distributed_tpu.utils.logging import log_event


def _targets(cfg: ModelConfig) -> dict[str, tuple[tuple, tuple, int | None]]:
    """target name -> (A shape, B shape, TP axis) where the shapes are
    per-tenant WITH the leading layer dim ([L, in..] / [L, out..]; the
    rank dim is appended/inserted by the registry) and the TP axis is
    the B-factor axis (indexed on the B shape) that shards under tensor
    parallelism — None marks a ROW-parallel target whose A factor
    contracts the sharded input dim instead."""
    l, e = cfg.n_layer, cfg.n_embd
    h, d = cfg.n_head, cfg.head_dim
    if cfg.family == "gpt2":
        return {
            "q": ((l, e), (l, h, d), 1),  # query third of fused c_attn
            "c_proj": ((l, e), (l, e), None),  # attention out (row-par)
        }
    if cfg.family == "llama":
        return {
            "wq": ((l, e), (l, h * d), 1),
            "wo": ((l, h * d), (l, e), None),
        }
    raise KeyError(f"unknown model family {cfg.family!r}")


class AdapterRegistry:
    """Per-tenant low-rank adapter store for ONE model config. Build
    once, share across every replica engine (the router's
    ``make_engine`` closure): tenant slots are then consistent across
    failover adoption. All tenants share one ``rank`` — the traced
    operand shape bakes it in, and per-tenant ranks would be per-tenant
    compiles, exactly what this subsystem exists to avoid."""

    def __init__(
        self, cfg: ModelConfig, *, rank: int, max_tenants: int = 8
    ) -> None:
        if rank < 1:
            raise ValueError(
                f"LoRA rank must be >= 1, got {rank}: a rank-0 adapter "
                "is the zero map — register no adapter (tenant slot 0 "
                "is already the shared zero adapter) instead of paying "
                "two einsums per projection for nothing"
            )
        if max_tenants < 1:
            raise ValueError(
                f"max_tenants must be >= 1, got {max_tenants}"
            )
        if cfg.n_experts:
            raise NotImplementedError(
                "LoRA adapters do not cover MoE configs (routed expert "
                "weights have no single projection to adapt) — serve "
                "dense gpt2/llama configs"
            )
        self.cfg = cfg
        self.rank = int(rank)
        self.max_tenants = int(max_tenants)
        self._targets = _targets(cfg)
        slots = self.max_tenants + 1  # slot 0 = the zero adapter
        self._host: dict[str, dict[str, np.ndarray]] = {}
        for name, (a_shape, b_shape, _) in self._targets.items():
            l = a_shape[0]
            self._host[name] = {
                # Stacked [L, slots, ...] — layer-major so scan_layers
                # slices the layer dim exactly like the base blocks.
                "a": np.zeros(
                    (l, slots) + a_shape[1:] + (self.rank,), np.float32
                ),
                "b": np.zeros(
                    (l, slots, self.rank) + b_shape[1:], np.float32
                ),
            }
        self._slots: dict[str, int] = {}
        self.version = 0
        self._device: tuple[int, dict] | None = None

    def tenants(self) -> tuple[str, ...]:
        return tuple(self._slots)

    def slot(self, tenant_id) -> int:
        """Tenant id -> adapter slot; unknown tenants are rejected
        loudly at every submit entry point (engine, router, HTTP 400)."""
        s = self._slots.get(tenant_id)
        if s is None:
            raise ValueError(
                f"unregistered tenant {tenant_id!r}: known tenants are "
                f"{sorted(map(repr, self._slots))} — register adapters "
                "with AdapterRegistry.register before submitting"
            )
        return s

    def register(
        self, tenant_id, adapters: dict | None = None, *,
        key=None, scale: float = 1.0,
    ) -> int:
        """Install one tenant's adapters into the next free slot and
        return it. Either pass ``adapters`` — {target: {"a": [L, ..in,
        r], "b": [L, r, ..out]}} matching this config's target shapes —
        or a PRNG ``key`` for a random NONZERO init (tests/benches; a
        real deployment loads trained factors). ``scale`` is the usual
        LoRA alpha/r factor, folded into B host-side so the trace pays
        nothing for it. Values change, shapes never: registration can
        never recompile a warmed engine."""
        import jax

        if tenant_id in self._slots:
            raise ValueError(
                f"tenant {tenant_id!r} is already registered (slot "
                f"{self._slots[tenant_id]}); build a new registry to "
                "replace adapters — engines memoize the device tree by "
                "version, so silent in-place swaps would be a footgun"
            )
        if len(self._slots) >= self.max_tenants:
            raise ValueError(
                f"adapter registry is full ({self.max_tenants} "
                "tenants): raise max_tenants at construction (the "
                "stacked operand is preallocated, so capacity is a "
                "build-time choice)"
            )
        if adapters is None and key is None:
            raise ValueError(
                "register needs either explicit adapters= factors or a "
                "key= for random init"
            )
        slot = len(self._slots) + 1
        for i, (name, (a_shape, b_shape, _)) in enumerate(
            self._targets.items()
        ):
            a_full = a_shape + (self.rank,)
            b_full = (b_shape[0], self.rank) + b_shape[1:]
            if adapters is not None:
                got = adapters.get(name)
                if got is None:
                    raise ValueError(
                        f"adapters missing target {name!r} (this config "
                        f"adapts {sorted(self._targets)})"
                    )
                a = np.asarray(got["a"], np.float32)
                b = np.asarray(got["b"], np.float32)
                if a.shape != a_full or b.shape != b_full:
                    raise ValueError(
                        f"tenant {tenant_id!r} target {name!r}: factor "
                        f"shapes {a.shape}/{b.shape} do not match the "
                        f"config's {a_full}/{b_full} (rank={self.rank})"
                    )
            else:
                ka, kb = jax.random.split(jax.random.fold_in(key, i))
                a = 0.02 * np.asarray(
                    jax.random.normal(ka, a_full), np.float32
                )
                b = 0.02 * np.asarray(
                    jax.random.normal(kb, b_full), np.float32
                )
            self._host[name]["a"][:, slot] = a
            self._host[name]["b"][:, slot] = b * (scale / self.rank)
        self._slots[tenant_id] = slot
        self.version += 1
        log_event(
            "tenant_register", tenant=str(tenant_id), slot=slot,
            rank=self.rank,
        )
        return slot

    def device_tree(self) -> dict:
        """The stacked adapter operand tree as device arrays, memoized
        per registry version (one upload per registration, not per
        dispatch)."""
        import jax.numpy as jnp

        if self._device is None or self._device[0] != self.version:
            self._device = (
                self.version,
                {
                    name: {
                        "a": jnp.asarray(leaves["a"]),
                        "b": jnp.asarray(leaves["b"]),
                    }
                    for name, leaves in self._host.items()
                },
            )
        return self._device[1]

    def partition_specs(self, tensor_axis: str = "tensor") -> dict:
        """PartitionSpec tree matching ``device_tree`` for the TP
        shard_map in_specs: column-parallel B factors shard their output
        axis with the base weight, row-parallel targets (``c_proj`` /
        ``wo``) shard the A factor's contracting dim instead — the
        delta partial then joins the base partial BEFORE the existing
        tp_reduce psum (``decode.lora_delta`` is collective-free), so
        the pinned all-reduce count is unchanged."""
        from jax.sharding import PartitionSpec as P

        specs: dict = {}
        for name, (a_shape, b_shape, b_axis) in self._targets.items():
            # Stacked layouts: a = [L, slots, in.., r], b = [L, slots,
            # r, out..]; axis indices below count on those.
            a_spec = [None] * (len(a_shape) + 2)
            b_spec = [None] * (len(b_shape) + 2)
            if b_axis is not None:  # column-parallel: B out dim shards
                b_spec[2 + b_axis] = tensor_axis
            else:  # row-parallel: A contracts the sharded input dim
                a_spec[2] = tensor_axis
            specs[name] = {"a": P(*a_spec), "b": P(*b_spec)}
        return specs
