"""Health-checked multi-replica router: the serving tier over N engines.

One ``BatchedDecodeEngine`` (or paged subclass) is a single failure
domain: when its device dies, everything in flight dies with it unless
the caller snapshots and rebuilds by hand. Millions of users hit a
SERVICE, and a service needs the layer above the engine — placement,
health, failover, and honest overload behaviour. ``ReplicaRouter`` is
that layer, and it is HOST-SIDE ONLY: replicas stay independent failure
domains running the exact compiled programs the audit registry pins
(MPMD-style independence, PAPERS.md #3 — one big mesh would make every
fault global), and nothing the router does can recompile a program,
perturb a neighbour row, or move a pinned collective budget.

The contract, per concern:

- **Routing + admission** (``submit``): each request goes to the
  least-loaded routable replica, scored on the uniform
  ``engine.stats()`` snapshot — queue depth AND page pressure (a paged
  replica without page headroom is not a candidate even if its queue is
  short; prompt tokens with no pages behind them are just a deeper
  queue). DEGRADED replicas rank strictly after HEALTHY ones, so a
  browned-out replica keeps draining what it has but stops attracting
  new load. Ties break by replica id: routing is a deterministic
  function of (request order, replica states), which is what makes
  storm runs replayable.
- **Load shedding**: when no replica is admissible the router raises
  ``lifecycle.RouterOverloaded`` (with a drain-time ``retry_after_s``)
  instead of queueing unboundedly — the SLO-aware choice: a bounded
  queue keeps p99 meaningful, and the client that retries after the
  hint lands in a drained router. The front door maps it to
  429 + Retry-After.
- **Failover** (replica death): a replica that dies mid-decode — its
  engine raising ``DispatchFailure`` from ``step``, or silent process
  loss (``kill``, chaos-injected via ``RouterFaultInjector``) — has
  every in-flight request converted to a PR-6 resume entry (clean
  tokens-so-far + pre-folded PRNG schedule, via the engine's own
  host-side ``snapshot``) and ADOPTED by survivors
  (``engine.adopt``). Continuation is BIT-IDENTICAL to an
  uninterrupted run because the entry + shared params fully determine
  the remaining tokens — which engine runs them is irrelevant. Zero
  lost rids, zero duplicated rids, zero new compiles on survivors
  (resume prefills ride warmed shapes). With NO survivor the entries
  park in the router and re-adopt when a replica comes back: total
  fleet loss degrades to queueing, never to data loss.
- **Drain / restart** (planned maintenance): ``drain`` captures the
  replica's host state as a snapshot (in-flight rows become resume
  entries; undelivered results are delivered, not cloned) and takes it
  out of rotation; ``restart`` rebuilds the engine, re-warms it, and
  ``restore``s the snapshot — the drained requests continue
  bit-identically on the restarted replica with zero lost or
  duplicated rids. ``drain(migrate=True)`` hands the work to survivors
  instead (the kill path without the fault).
- **Brown-out**: per-replica step latency rides an EMA on the router's
  clock; a replica whose EMA exceeds ``degrade_factor`` x the fleet
  median (plus the ``degrade_min_s`` floor) turns DEGRADED and stops
  attracting new load until it recovers — one slow replica inflates
  its own latencies, not the fleet p99. Chaos drives this
  deterministically: a per-replica ``FaultInjector`` slow_tick on a
  shared ``VirtualClock``.

Request ids: the router issues its own monotonically-increasing rids
and maps them onto per-engine rids (re-mapped on every adoption);
results are re-labelled so a client never sees engine-internal ids.
Every lifecycle transition logs through ``utils/logging.log_event``
with the router vocabulary (``route`` / ``shed`` / ``failover`` /
``drain`` / ``replica_down`` / ``replica_up`` / ``replica_degraded`` /
``replica_recovered``) carrying rid + replica id — docs/ROBUSTNESS.md
§13 documents the schema; a storm run is diagnosable from the JSONL
log alone.

Not thread-safe (one dispatcher per router — the asyncio front door in
serving/server.py serialises through a lock). Replicas must share ONE
params tree and, when deadlines or virtual-time chaos are in play, one
clock (pass the same ``clock`` to the router and every engine).
"""

from __future__ import annotations

import dataclasses
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

import numpy as np

from pytorch_distributed_tpu.serving.lifecycle import (
    ABORTED,
    DispatchFailure,
    EngineSnapshot,
    RequestResult,
    RouterOverloaded,
)
from pytorch_distributed_tpu.utils.logging import log_event

HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
DRAINED = "DRAINED"
DOWN = "DOWN"
REPLICA_STATES = (HEALTHY, DEGRADED, DRAINED, DOWN)
_ROUTABLE = (HEALTHY, DEGRADED)


@dataclasses.dataclass
class _Replica:
    """One replica's router-side record: the engine, its health state,
    the engine-rid -> router-rid map, and the compile-count watermark
    the zero-steady-compile assertion is measured against."""

    rep_id: int
    engine: Any
    state: str = HEALTHY
    tick_ema_s: float | None = None  # None until the first measured tick
    rid_map: dict[int, int] = dataclasses.field(default_factory=dict)
    warm_count: int = 0
    held_snapshot: EngineSnapshot | None = None  # parked by drain()
    down_reason: str = ""


class ReplicaRouter:
    """See module docstring. ``make_engine(rep_id)`` builds one replica
    engine (called at construction and again on every ``restart`` — the
    factory IS the restart path, so it must return a fresh idle engine
    each call); ``n_replicas`` fixes the fleet size for the router's
    life. Health knobs:

    - ``shed_queue_depth``: a replica whose engine queue is this deep is
      not admissible (default: 2x its slot count).
    - ``shed_page_free``: a paged replica with fewer free pages is not
      admissible (default 1 — "has any headroom at all"; raise it to
      shed earlier under page pressure).
    - ``degrade_factor`` / ``degrade_min_s`` / ``ema_alpha``: brown-out
      detection — DEGRADED when the replica's step-latency EMA exceeds
      ``max(degrade_min_s, degrade_factor * fleet-median EMA)``;
      recovery is the same test passing again.
    - ``retry_after_s``: the shed hint when the drain estimate has no
      signal (fleet fully down); otherwise the estimate is derived from
      the median step EMA and the shallowest queue.
    - ``parallel_step``: step busy replicas concurrently (one host
      thread per replica) instead of round-robin. With per-replica
      device placement (``MeshConfig.device_ids`` / engine ``device=``)
      the replicas' XLA dispatches overlap on disjoint device slices —
      the wall-clock win scripts/loadgen.py measures. Engine ticks stay
      single-threaded per engine; all router bookkeeping (health,
      delivery, failover, handoffs) runs serially after the joins, so
      determinism contracts are untouched. Default False: virtual-clock
      tests and chaos schedules assume sequential stepping.
    """

    def __init__(
        self,
        make_engine: Callable[[int], Any],
        n_replicas: int,
        *,
        clock=None,
        shed_queue_depth: int | None = None,
        shed_page_free: int = 1,
        degrade_factor: float = 4.0,
        degrade_min_s: float = 0.05,
        ema_alpha: float = 0.3,
        retry_after_s: float = 1.0,
        parallel_step: bool = False,
    ) -> None:
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self._make_engine = make_engine
        self._clock = clock or time.monotonic
        self._replicas = [
            _Replica(rep_id=i, engine=make_engine(i))
            for i in range(n_replicas)
        ]
        for r in self._replicas:
            self._log_role(r)
        self.shed_queue_depth = shed_queue_depth
        self.shed_page_free = int(shed_page_free)
        self.degrade_factor = float(degrade_factor)
        self.degrade_min_s = float(degrade_min_s)
        self.ema_alpha = float(ema_alpha)
        self.retry_after_s = float(retry_after_s)
        self.parallel_step = bool(parallel_step)
        self._next_rid = 0
        # router rid -> (rep_id, engine rid); the mirror of each
        # replica's rid_map. Entries leave on terminal delivery.
        self._assign: dict[int, tuple[int, int]] = {}
        # Entries with no live replica to run them: (router rid,
        # _Pending). Re-adopted at the next tick with a routable replica.
        self._orphans: list[tuple[int, Any]] = []
        # Session stickiness: router sid -> (rep_id, engine sid). Turns
        # of one session must land on the replica holding its pinned
        # prefix pages; on replica loss the session re-homes to a
        # survivor (fresh engine sid — the transcript-carrying
        # resubmission makes that lossless, at one cold prefill).
        self._sessions: dict[int, tuple[int, int]] = {}
        self._next_sid = 0
        self.results: dict[int, RequestResult] = {}
        self._ticks = 0
        self._injector = None  # serving/chaos.RouterFaultInjector
        self.counters: dict[str, int] = {
            "routed": 0, "shed": 0, "failovers": 0, "failover_requests": 0,
            "drains": 0, "restarts": 0, "orphaned": 0,
            "sessions_opened": 0, "session_rehomes": 0,
            "handoffs": 0,
        }

    # -- fleet management ---------------------------------------------------

    @staticmethod
    def _role(r: _Replica) -> str:
        """The replica's disaggregation role. Engines without the knob
        (dense engines, pre-disagg paged builds) are colocated."""
        return getattr(r.engine, "role", "colocated")

    def _log_role(self, r: _Replica) -> None:
        log_event(
            "role_assign", replica=r.rep_id, role=self._role(r),
            device_ids=(
                r.engine.device_ids()
                if hasattr(r.engine, "device_ids") else None
            ),
            t=round(self._clock(), 6),
        )

    def warmup(self, params) -> int:
        """Warm every replica's compile set and record the per-replica
        watermark ``steady_compiles`` is measured against. Returns the
        fleet-total compile count."""
        for r in self._replicas:
            r.engine.warmup(params)
            r.warm_count = r.engine.compile_count()
        return sum(r.engine.compile_count() for r in self._replicas)

    def steady_compiles(self) -> dict[int, int]:
        """Per-replica compiles since its warmup watermark — expected 0
        for every replica that was warmed and never rebuilt (failover
        re-prefills ride warmed shapes by construction)."""
        return {
            r.rep_id: r.engine.compile_count() - r.warm_count
            for r in self._replicas
        }

    def replica_states(self) -> dict[int, str]:
        return {r.rep_id: r.state for r in self._replicas}

    def live_replicas(self) -> list[int]:
        return [r.rep_id for r in self._replicas if r.state in _ROUTABLE]

    def set_fault_injector(self, injector) -> None:
        """Install a ``serving/chaos.RouterFaultInjector`` (or None):
        consulted once per ``step`` for replica_kill faults. Host-side
        only, like every other injection point."""
        self._injector = injector

    # -- admission ----------------------------------------------------------

    def _admissible(self, r: _Replica) -> tuple[float, ...] | None:
        """Admission + scoring in one read of the replica's uniform
        ``stats()``: None = not admissible (saturated queue or page
        starvation); otherwise the routing sort key — DEGRADED after
        HEALTHY, then least host load, then page pressure, then id.
        DECODE workers are never admissible: fresh prompts are prefill
        work and reach them only as kv handoffs (regression-pinned in
        tests/test_serving_disagg.py)."""
        if self._role(r) == "decode":
            return None
        st = r.engine.stats()
        limit = (
            self.shed_queue_depth
            if self.shed_queue_depth is not None
            else 2 * (st["slots"] or 1)
        )
        if st["queue_depth"] >= limit:
            return None
        page_pressure = 0.0
        if st["free_pages"] is not None:
            if st["free_pages"] < self.shed_page_free:
                return None
            # Session-pinned pages count as UNAVAILABLE capacity: they
            # are off the allocator's table until their session goes
            # idle, so a session-heavy replica must look loaded before
            # it starts preempting for its pinned residents
            # (regression-pinned in tests/test_serving_scenarios.py).
            # Speculative width (engine speculative_k) deliberately
            # does NOT enter this accounting: a speculating row's draft
            # window lives on its own already-counted private tail
            # pages (grown best-effort, never by preemption —
            # engine._grow_for_drafts), so pages_in_use is the truth
            # for spec and non-spec replicas alike; scoring a spec
            # replica as (k+1)x wider would starve-exclude the FASTER
            # replica.
            pinned = st.get("session_pinned_pages") or 0
            page_pressure = (
                st["pages_in_use"] + pinned
            ) / max(1, st["pool_pages"])
        load = st["queue_depth"] + st["active_rows"]
        return (
            1.0 if r.state == DEGRADED else 0.0,
            float(load),
            page_pressure,
            float(r.rep_id),
        )

    def _ranked_replicas(self) -> list[_Replica]:
        """Admissible replicas, best routing choice first."""
        scored = []
        for r in self._replicas:
            if r.state not in _ROUTABLE:
                continue
            key = self._admissible(r)
            if key is not None:
                scored.append((key, r))
        return [r for _, r in sorted(scored, key=lambda kr: kr[0])]

    def _retry_after(self) -> float:
        """Drain-time hint for a shed response: one slot's worth of
        decode at the fleet's median measured tick latency, floored at
        the configured default. Deliberately rough — its job is to
        spread retries out, not to promise capacity."""
        emas = sorted(
            r.tick_ema_s for r in self._replicas
            if r.state in _ROUTABLE and r.tick_ema_s is not None
        )
        if not emas:
            return self.retry_after_s
        med = emas[len(emas) // 2]
        depth = min(
            r.engine.stats()["queue_depth"] for r in self._replicas
            if r.state in _ROUTABLE
        )
        return max(self.retry_after_s, med * (depth + 1))

    def open_session(self) -> int:
        """Open a multi-turn session on the least-loaded routable
        replica (it must be paged — sessions ride the pinned prefix
        cache); returns the ROUTER session id ``submit(session=)``
        takes. The router owns the sid -> (replica, engine sid)
        stickiness map and re-homes the session to a survivor on
        replica loss."""
        best = self._least_loaded(colocated_only=True)
        if best is None:
            raise RouterOverloaded(
                "no live colocated replica to open a session on — "
                "sessions pin prefix pages where their turns both "
                "prefill AND decode, so prefill/decode workers cannot "
                f"host them (states {self.replica_states()})",
                retry_after_s=self._retry_after(),
            )
        if not hasattr(best.engine, "open_session"):
            raise ValueError(
                "sessions need paged replica engines "
                "(PagedBatchedDecodeEngine) — this fleet serves "
                f"{type(best.engine).__name__}"
            )
        esid = best.engine.open_session()
        sid = self._next_sid
        self._next_sid += 1
        self._sessions[sid] = (best.rep_id, esid)
        self.counters["sessions_opened"] += 1
        log_event(
            "session_route", session=sid, replica=best.rep_id,
            engine_session=esid, t=round(self._clock(), 6),
        )
        return sid

    def close_session(self, sid: int) -> None:
        """Close a router session; the replica's pins release. Unknown
        sids raise (loudly, like the engine's own close)."""
        loc = self._sessions.pop(sid, None)
        if loc is None:
            raise ValueError(
                f"unknown router session id {sid}: open_session() "
                "first (or it was already closed)"
            )
        rep_id, esid = loc
        r = self._replicas[rep_id]
        if r.state in _ROUTABLE:
            r.engine.close_session(esid)
        # A DOWN/DRAINED holder's tracker died (or will be rebuilt)
        # with its engine — nothing to release.

    def _session_target(self, sid: int) -> tuple[_Replica, int]:
        """The (replica, engine sid) a session turn must route to,
        re-homing onto a survivor when the sticky replica is not
        routable — a fresh engine session whose empty transcript any
        resubmitted conversation extends (one cold prefill, no data
        loss, counted as ``session_rehomes``)."""
        loc = self._sessions.get(sid)
        if loc is None:
            raise ValueError(
                f"unknown router session id {sid}: open_session() "
                "first (or it was closed)"
            )
        rep_id, esid = loc
        r = self._replicas[rep_id]
        if r.state in _ROUTABLE:
            return r, esid
        best = self._least_loaded(colocated_only=True)
        if best is None:
            raise RouterOverloaded(
                f"session {sid}'s replica {rep_id} is {r.state} and no "
                "survivor can re-home it",
                retry_after_s=self._retry_after(),
            )
        esid = best.engine.open_session()
        self._sessions[sid] = (best.rep_id, esid)
        self.counters["session_rehomes"] += 1
        log_event(
            "session_route", session=sid, replica=best.rep_id,
            engine_session=esid, rehomed_from=rep_id,
            t=round(self._clock(), 6),
        )
        return best, esid

    def submit(self, prompt, max_new_tokens: int, *,
               session: int | None = None, **kw) -> int:
        """Route one request (``engine.submit`` kwargs pass through —
        deadlines via ``timeout_s=``, SLO tiers via ``priority=`` and
        tenants via ``tenant=`` land on the replica engine). Returns
        the ROUTER rid its terminal ``RequestResult`` will carry in
        ``results`` / ``pop_result``. Raises ``RouterOverloaded`` (with
        ``retry_after_s``) when no replica is admissible.

        ``session=`` (a router sid from ``open_session``) routes STICKY
        to the replica holding the session's pinned pages instead of
        least-loaded — the pages ARE the locality."""
        from pytorch_distributed_tpu.serving.lifecycle import (
            AdmissionQueueFull,
        )

        r = erid = None
        if session is not None:
            r, esid = self._session_target(session)
            if self._admissible(r) is None:
                # Stickiness cannot spill to another replica (the pages
                # live here), but the SLO gate still applies: past the
                # router's shed thresholds the holder sheds like a
                # saturated fleet — without this, an engine with
                # queue_limit=None would let session turns queue
                # unboundedly while plain traffic is 429'd.
                self.counters["shed"] += 1
                hint = self._retry_after()
                raise RouterOverloaded(
                    f"session {session}'s replica {r.rep_id} is past "
                    f"its admission threshold; retry after "
                    f"~{hint:.2f}s",
                    retry_after_s=hint,
                )
            try:
                erid = r.engine.submit(
                    prompt, max_new_tokens, session=esid, **kw
                )
            except AdmissionQueueFull as err:
                # Stickiness cannot spill to another replica (the pages
                # live here): a saturated holder sheds like a saturated
                # fleet.
                self.counters["shed"] += 1
                hint = self._retry_after()
                raise RouterOverloaded(
                    f"session {session}'s replica {r.rep_id} is "
                    f"saturated ({err}); retry after ~{hint:.2f}s",
                    retry_after_s=hint,
                ) from None
        else:
            for cand in self._ranked_replicas():
                try:
                    erid = cand.engine.submit(prompt, max_new_tokens, **kw)
                    r = cand
                    break
                except AdmissionQueueFull:
                    # The engine's own queue_limit can be tighter than
                    # the router's threshold — that replica is
                    # saturated, try the next; all-saturated sheds
                    # below like any other overload.
                    continue
        if r is None:
            self.counters["shed"] += 1
            hint = self._retry_after()
            log_event(
                "shed", t=round(self._clock(), 6),
                live=len(self.live_replicas()),
                retry_after_s=round(hint, 4),
            )
            raise RouterOverloaded(
                "every routable replica is past its admission threshold "
                f"(states {self.replica_states()}); retry after "
                f"~{hint:.2f}s",
                retry_after_s=hint,
            )
        rid = self._next_rid
        self._next_rid += 1
        r.rid_map[erid] = rid
        self._assign[rid] = (r.rep_id, erid)
        self.counters["routed"] += 1
        log_event(
            "route", rid=rid, replica=r.rep_id, engine_rid=erid,
            state=r.state, t=round(self._clock(), 6),
        )
        return rid

    # -- results ------------------------------------------------------------

    def _deliver(self, r: _Replica, erid: int, res: RequestResult) -> int:
        rid = r.rid_map.pop(erid)
        self._assign.pop(rid, None)
        self.results[rid] = dataclasses.replace(res, rid=rid)
        return rid

    def pop_result(self, rid: int) -> RequestResult:
        """Deliver + release one terminal result (the engine
        ``pop_result`` discipline at router scope)."""
        return self.results.pop(rid)

    def abort(self, rid: int) -> bool:
        """Cancel one request wherever it lives — queued/active on a
        replica, or parked as an orphan. Same semantics as
        ``engine.abort``: True on transition, False if already
        terminal, KeyError for unknown rids."""
        if rid in self.results:
            return False
        for i, (orid, q) in enumerate(self._orphans):
            if orid == rid:
                del self._orphans[i]
                self.results[rid] = RequestResult(
                    rid=rid, state=ABORTED,
                    tokens=np.concatenate([
                        np.asarray(q.prompt, np.int32),
                        np.asarray(q.gen, np.int32),
                    ]),
                    reason="abort() while parked (no live replica)",
                )
                return True
        loc = self._assign.get(rid)
        if loc is None:
            raise KeyError(
                f"unknown router rid {rid}: never submitted, or already "
                "delivered via pop_result"
            )
        rep_id, erid = loc
        r = self._replicas[rep_id]
        if r.engine.abort(erid):
            # A DRAINED replica's held snapshot still carries the entry;
            # scrub it, or restart would resurrect (and re-run) a
            # request the client cancelled — and its re-delivery would
            # hit an already-popped rid_map entry.
            if r.held_snapshot is not None:
                r.held_snapshot.pending = [
                    q for q in r.held_snapshot.pending if q.rid != erid
                ]
            self._deliver(r, erid, r.engine.pop_result(erid))
            return True
        return False

    def progress(self, rid: int):
        """Tokens-so-far for a live or terminal router rid (the SSE
        streaming read) — None for unknown rids."""
        if rid in self.results:
            return np.asarray(self.results[rid].tokens)
        for orid, q in self._orphans:
            if orid == rid:
                return np.concatenate([
                    np.asarray(q.prompt, np.int32),
                    np.asarray(q.gen, np.int32),
                ])
        loc = self._assign.get(rid)
        if loc is None:
            return None
        rep_id, erid = loc
        return self._replicas[rep_id].engine.peek_tokens(erid)

    def has_work(self) -> bool:
        return bool(self._orphans) or any(
            r.state in _ROUTABLE and r.engine.has_work()
            for r in self._replicas
        )

    # -- the tick -----------------------------------------------------------

    def step(self, params) -> list[int]:
        """One router tick: fire chaos, re-adopt orphans, then advance
        every routable replica one engine tick — measuring its latency
        for brown-out detection, catching ``DispatchFailure`` as
        replica death — and deliver every terminal result under ROUTER
        rids. Returns the router rids that reached a terminal state."""
        self._ticks += 1
        if self._injector is not None:
            self._injector.on_tick(self._ticks)
            # Drain EVERY armed kill (a correlated-failure schedule may
            # script several on one tick), re-reading the live set after
            # each — a kill changes it.
            while True:
                target = self._injector.pop_kill(self.live_replicas())
                if target is None:
                    break
                self.kill(target, reason="chaos replica_kill")
        self._readopt_orphans()
        finished: list[int] = []

        def _idle(r: _Replica) -> bool:
            if r.engine.has_work():
                return False
            # An idle DEGRADED replica would stay deprioritized
            # forever (no ticks -> no EMA evidence): decay its EMA
            # optimistically instead — DEGRADED only deprioritizes,
            # so a premature recovery costs one slow tick, not an
            # outage.
            if r.state == DEGRADED:
                self._update_health(r, 0.0)
            return True

        def _one(r: _Replica):
            t0 = self._clock()
            try:
                done = r.engine.step(params)
            except DispatchFailure as err:
                return r, self._clock() - t0, None, err
            return r, self._clock() - t0, done, None

        def _settle(r: _Replica, dt: float, done, err) -> None:
            if err is not None:
                # The engine exhausted its own retry budget and left its
                # state consistent (everything requeued) — at the router
                # tier that IS replica death; survivors take the work.
                self._take_down(
                    r, f"dispatch failure: {err}", finished=finished
                )
                return
            self._update_health(r, dt)
            for erid in done:
                finished.append(
                    self._deliver(r, erid, r.engine.pop_result(erid))
                )

        if self.parallel_step:
            busy = [
                r for r in self._replicas
                if r.state in _ROUTABLE and not _idle(r)
            ]
            if len(busy) > 1:
                # Each replica's dispatch overlaps on its own device
                # slice; everything mutable at router scope waits for
                # the joins.
                with ThreadPoolExecutor(max_workers=len(busy)) as pool:
                    stepped = list(pool.map(_one, busy))
            else:
                stepped = [_one(r) for r in busy]
            for r, dt, done, err in stepped:
                _settle(r, dt, done, err)
        else:
            # Settle inline, re-reading routability and has_work at each
            # replica's turn: a mid-tick death's failover entries can be
            # adopted — and stepped — by replicas LATER this same tick.
            for r in self._replicas:
                if r.state not in _ROUTABLE or _idle(r):
                    continue
                _settle(*_one(r))
        self._pump_handoffs(finished)
        return finished

    # -- disaggregation: kv handoff pump ------------------------------------

    def _handoff_target(self, h) -> _Replica | None:
        """Best routable replica to continue a finished prefill: never a
        PREFILL worker (the role pin's other direction — decode work
        does not route to prefill-only replicas), must pass the
        engine-side geometry/capacity gate (``can_import_handoff``),
        preferring HEALTHY then lowest page pressure (pages are what a
        handoff consumes) then lightest host load, id tie-break."""
        best, best_key = None, None
        for r in self._replicas:
            if r.state not in _ROUTABLE or self._role(r) == "prefill":
                continue
            eng = r.engine
            if not (hasattr(eng, "can_import_handoff")
                    and eng.can_import_handoff(h)):
                continue
            st = eng.stats()
            pinned = st.get("session_pinned_pages") or 0
            pressure = (
                (st["pages_in_use"] + pinned) / max(1, st["pool_pages"])
                if st.get("free_pages") is not None else 0.0
            )
            key = (
                1.0 if r.state == DEGRADED else 0.0,
                pressure,
                float(st["queue_depth"] + st["active_rows"]),
                float(r.rep_id),
            )
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _pump_handoffs(self, finished: list[int]) -> None:
        """Move every finished prefill off its PREFILL worker onto a
        decode-capable replica. Source rows stay live (resume-entry
        fallback) until ``complete_handoff`` — a crash on either side
        mid-handoff degrades to the ordinary failover path, never to a
        lost or duplicated rid. No target this tick just parks the row;
        it is retried next tick (prefill workers park ready rows
        rather than decoding them)."""
        for src in self._replicas:
            if src.state not in _ROUTABLE or self._role(src) != "prefill":
                continue
            seng = src.engine
            for erid in list(seng.handoff_ready()):
                t0 = self._clock()
                h = seng.export_handoff(erid)
                dst = self._handoff_target(h)
                if dst is None:
                    continue
                eng_fin: list[int] = []
                try:
                    new_erid = dst.engine.import_handoff(h, eng_fin)
                except DispatchFailure as err:
                    # _take_down snapshots the destination and delivers
                    # EVERY undelivered result — including rows the
                    # failed import's recovery terminally FAILED — so
                    # eng_fin must not be delivered again here.
                    self._take_down(
                        dst, f"kv_import dispatch failure: {err}",
                        finished=finished,
                    )
                    continue
                # Recovery inside a survivable failed import can
                # terminally FAIL rows on the destination (retry budget
                # exhausted) — deliver them like step() would.
                for fe in eng_fin:
                    finished.append(self._deliver(
                        dst, fe, dst.engine.pop_result(fe)
                    ))
                if new_erid is None:
                    continue  # no row/pages after all — retry next tick
                rid = src.rid_map.pop(erid)
                dst.rid_map[new_erid] = rid
                self._assign[rid] = (dst.rep_id, new_erid)
                seng.complete_handoff(erid)
                self.counters["handoffs"] += 1
                log_event(
                    "kv_handoff", rid=rid, from_replica=src.rep_id,
                    to_replica=dst.rep_id, pages=h.n_pages,
                    bytes=h.wire_bytes, useful_bytes=h.useful_bytes,
                    export_s=round(h.export_s, 6),
                    latency_s=round(self._clock() - t0, 6),
                    t=round(self._clock(), 6),
                )

    def run(self, params, *, max_ticks: int | None = None) -> list[int]:
        """Drive ``step`` until idle (or ``max_ticks``); returns every
        router rid that finished during the drive."""
        finished: list[int] = []
        ticks = 0
        while self.has_work():
            if max_ticks is not None and ticks >= max_ticks:
                break
            finished += self.step(params)
            ticks += 1
        return finished

    def _update_health(self, r: _Replica, dt: float) -> None:
        a = self.ema_alpha
        r.tick_ema_s = (
            dt if r.tick_ema_s is None
            else (1 - a) * r.tick_ema_s + a * dt
        )
        others = [
            x.tick_ema_s for x in self._replicas
            if x is not r and x.state in _ROUTABLE
            and x.tick_ema_s is not None
        ]
        if not others:
            # No peer baseline (single-replica fleet, or the first
            # replica to ever tick): "slow" is only meaningful RELATIVE
            # to the fleet, so judging against the degrade_min_s floor
            # alone would brand every replica of a slow model DEGRADED.
            return
        med = sorted(others)[len(others) // 2]
        threshold = max(self.degrade_min_s, self.degrade_factor * med)
        if r.state == HEALTHY and r.tick_ema_s > threshold:
            r.state = DEGRADED
            log_event(
                "replica_degraded", replica=r.rep_id,
                tick_ema_s=round(r.tick_ema_s, 4),
                threshold_s=round(threshold, 4),
                t=round(self._clock(), 6),
            )
        elif r.state == DEGRADED and r.tick_ema_s <= threshold:
            r.state = HEALTHY
            log_event(
                "replica_recovered", replica=r.rep_id,
                tick_ema_s=round(r.tick_ema_s, 4),
                t=round(self._clock(), 6),
            )

    # -- failover / drain / restart ----------------------------------------

    def kill(self, rep_id: int, *, reason: str = "process loss") -> None:
        """Treat one replica as a lost process: its device state (and
        engine object) are written off, every in-flight/queued request
        fails over to survivors from the engine's host-side snapshot.
        Idempotent on already-down replicas (a chaos schedule may kill a
        corpse)."""
        r = self._replicas[rep_id]
        if r.state == DOWN:
            return
        self._take_down(r, reason)

    def _take_down(self, r: _Replica, reason: str,
                   finished: list[int] | None = None) -> None:
        snap = r.engine.snapshot()
        r.state = DOWN
        r.down_reason = reason
        r.held_snapshot = None
        log_event(
            "replica_down", replica=r.rep_id, reason=reason,
            pending=len(snap.pending), t=round(self._clock(), 6),
        )
        # Undelivered terminal results are host memory — they survive
        # the replica and deliver now (their rids are NOT lost).
        for erid, res in snap.results.items():
            rid = self._deliver(r, erid, res)
            if finished is not None:
                finished.append(rid)
        self.counters["failovers"] += 1
        self._redistribute(r, snap.pending)
        r.rid_map.clear()

    def _least_loaded(self, exclude: _Replica | None = None, *,
                      colocated_only: bool = False):
        """Least-loaded routable replica for failover/re-adoption —
        same preference order as routing (HEALTHY before DEGRADED, then
        host load, then id) but WITHOUT the admission thresholds:
        failover must not shed accepted work, and engine-side deferral
        (page starvation) already degrades gracefully. DECODE workers
        are never candidates (a resume entry is re-PREFILL work — the
        decode-ward regression pin's mirror); ``colocated_only``
        additionally excludes PREFILL workers (sessions must live where
        their turns both prefill AND decode)."""
        best, best_key = None, None
        for r in self._replicas:
            if r is exclude or r.state not in _ROUTABLE:
                continue
            role = self._role(r)
            if role == "decode" or (colocated_only and role != "colocated"):
                continue
            st = r.engine.stats()
            key = (
                1.0 if r.state == DEGRADED else 0.0,
                float(st["queue_depth"] + st["active_rows"]),
                float(r.rep_id),
            )
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _redistribute(self, src: _Replica, pendings) -> None:
        """Re-route a dead/drained replica's entries (ascending rid =
        the replica's own FIFO order) onto least-loaded survivors; park
        what nothing can take."""
        for q in pendings:
            rid = src.rid_map.pop(q.rid)
            best = self._least_loaded(exclude=src)
            if best is None:
                self.counters["orphaned"] += 1
                self._orphans.append((rid, q))
                self._assign.pop(rid, None)
                log_event(
                    "failover", rid=rid, from_replica=src.rep_id,
                    to_replica=None, parked=True,
                    resumed_tokens=len(q.gen),
                    t=round(self._clock(), 6),
                )
                continue
            self._adopt_one(best, rid, q, from_replica=src.rep_id)

    def _adopt_one(self, r: _Replica, rid: int, q,
                   from_replica: int | None) -> None:
        new_erid = r.engine.adopt([q])[q.rid]
        r.rid_map[new_erid] = rid
        self._assign[rid] = (r.rep_id, new_erid)
        self.counters["failover_requests"] += 1
        log_event(
            "failover", rid=rid, from_replica=from_replica,
            to_replica=r.rep_id, resumed_tokens=len(q.gen),
            t=round(self._clock(), 6),
        )

    def _readopt_orphans(self) -> None:
        if not self._orphans:
            return
        orphans, self._orphans = self._orphans, []
        for rid, q in orphans:
            best = self._least_loaded()
            if best is None:
                self._orphans.append((rid, q))
            else:
                self._adopt_one(best, rid, q, from_replica=None)

    def drain(self, rep_id: int, *, migrate: bool = False) -> int:
        """Planned maintenance: snapshot the replica's host state and
        take it out of rotation. Default keeps the snapshot parked on
        the record — ``restart`` restores it and the drained requests
        continue bit-identically (zero lost, zero duplicated rids);
        ``migrate=True`` hands the work to survivors immediately (the
        failover path without the fault). Returns the number of
        requests captured. Draining the last routable replica with
        ``migrate=True`` parks the work (orphans) rather than refusing.
        """
        r = self._replicas[rep_id]
        if r.state not in _ROUTABLE:
            raise RuntimeError(
                f"replica {rep_id} is {r.state}; drain needs a routable "
                "replica"
            )
        snap = r.engine.snapshot()
        log_event(
            "drain", replica=rep_id, pending=len(snap.pending),
            migrate=migrate, t=round(self._clock(), 6),
        )
        self.counters["drains"] += 1
        # Undelivered results deliver NOW and are scrubbed from BOTH the
        # held snapshot (restore would hand the rid out twice) and the
        # still-live engine (a later kill() re-snapshots it and must not
        # re-deliver).
        for erid, res in list(snap.results.items()):
            r.engine.pop_result(erid)
            self._deliver(r, erid, res)
        snap.results = {}
        if migrate:
            r.state = DOWN
            r.down_reason = "drained (migrated)"
            self._redistribute(r, snap.pending)
            r.rid_map.clear()
        else:
            r.state = DRAINED
            r.down_reason = "drained (held for restart)"
            r.held_snapshot = snap
        return len(snap.pending)

    def restart(self, rep_id: int, params) -> None:
        """Bring a DOWN/DRAINED replica back: fresh engine from the
        factory, re-warmed (the restart pays its compile set ONCE, and
        the watermark resets so steady-compile assertions stay
        meaningful), drained snapshot restored if one is held. The
        replica re-enters rotation HEALTHY."""
        r = self._replicas[rep_id]
        if r.state in _ROUTABLE:
            raise RuntimeError(
                f"replica {rep_id} is {r.state}; restart needs a "
                "DOWN/DRAINED replica"
            )
        if r.state == DOWN:
            # Work was redistributed (or lost with the process) — any
            # stale engine-rid mappings died with the old engine.
            r.rid_map.clear()
        r.engine = self._make_engine(rep_id)
        self._log_role(r)
        r.engine.warmup(params)
        if r.held_snapshot is not None:
            r.engine.restore(r.held_snapshot)
            r.held_snapshot = None
        r.warm_count = r.engine.compile_count()
        r.state = HEALTHY
        r.tick_ema_s = None
        r.down_reason = ""
        # Router sessions still homed here point at the OLD engine's
        # sids — the fresh engine restarts its session counter, so a
        # stale esid would either read as unknown or collide with a
        # later open_session(). Re-home each onto a fresh engine session
        # on this replica (empty transcript; the next turn's resubmitted
        # conversation extends it — one cold prefill, no data loss).
        for sid, (home, _stale) in list(self._sessions.items()):
            if home != rep_id:
                continue
            esid = r.engine.open_session()
            self._sessions[sid] = (rep_id, esid)
            self.counters["session_rehomes"] += 1
            log_event(
                "session_route", session=sid, replica=rep_id,
                engine_session=esid, rehomed_from=rep_id,
                t=round(self._clock(), 6),
            )
        self.counters["restarts"] += 1
        log_event(
            "replica_up", replica=rep_id, t=round(self._clock(), 6),
        )

    # -- introspection ------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        """Router-tier snapshot: per-replica health + the uniform engine
        stats, router counters, and orphan depth — what ``/healthz``
        serves."""
        return {
            "replicas": {
                r.rep_id: dict(
                    state=r.state,
                    tick_ema_s=(
                        None if r.tick_ema_s is None
                        else round(r.tick_ema_s, 6)
                    ),
                    down_reason=r.down_reason or None,
                    **(
                        r.engine.stats() if r.state != DOWN
                        else {"engine": None}
                    ),
                )
                for r in self._replicas
            },
            "orphans": len(self._orphans),
            "undelivered_results": len(self.results),
            "sessions": len(self._sessions),
            "counters": dict(self.counters),
        }
