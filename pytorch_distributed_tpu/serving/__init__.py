"""Serving fast path: the persistent donated-KV decode engines (serial
per-request DecodeEngine + slot-scheduled continuous-batching
BatchedDecodeEngine), the request-lifecycle vocabulary (terminal states,
results, snapshots — serving/lifecycle.py) and the deterministic
fault-injection harness (serving/chaos.py)."""

from pytorch_distributed_tpu.serving.chaos import (  # noqa: F401
    Fault,
    FaultInjector,
    VirtualClock,
)
from pytorch_distributed_tpu.serving.block_pool import (  # noqa: F401
    BlockPool,
)
from pytorch_distributed_tpu.serving.engine import (  # noqa: F401
    BatchedDecodeEngine,
    BucketSpec,
    DecodeEngine,
    PagedBatchedDecodeEngine,
    shim_engine,
)
from pytorch_distributed_tpu.serving.lifecycle import (  # noqa: F401
    ABORTED,
    DONE,
    EXPIRED,
    FAILED,
    TERMINAL_STATES,
    AdmissionQueueFull,
    DispatchFailure,
    EngineSnapshot,
    PagePoolExhausted,
    RequestFailed,
    RequestResult,
)
