"""Serving stack: the persistent donated-KV decode engines (serial
per-request DecodeEngine + slot-scheduled continuous-batching
BatchedDecodeEngine + paged PagedBatchedDecodeEngine), the
request-lifecycle vocabulary (terminal states, results, snapshots —
serving/lifecycle.py), the deterministic fault-injection harness
(serving/chaos.py), the seeded workload generator
(serving/workload.py), and the serving TIER over them: the
health-checked multi-replica ReplicaRouter (serving/router.py) and the
asyncio HTTP/SSE front door (serving/server.py, imported directly to
keep this package import light)."""

from pytorch_distributed_tpu.serving.chaos import (  # noqa: F401
    Fault,
    FaultInjector,
    RouterFault,
    RouterFaultInjector,
    VirtualClock,
)
from pytorch_distributed_tpu.serving.router import (  # noqa: F401
    DEGRADED,
    DOWN,
    DRAINED,
    HEALTHY,
    REPLICA_STATES,
    ReplicaRouter,
)
from pytorch_distributed_tpu.serving.block_pool import (  # noqa: F401
    BlockPool,
)
from pytorch_distributed_tpu.serving.engine import (  # noqa: F401
    BatchedDecodeEngine,
    BucketSpec,
    DecodeEngine,
    PagedBatchedDecodeEngine,
    shim_engine,
)
from pytorch_distributed_tpu.serving.lifecycle import (  # noqa: F401
    ABORTED,
    DONE,
    EXPIRED,
    FAILED,
    TERMINAL_STATES,
    AdmissionQueueFull,
    DispatchFailure,
    EngineSnapshot,
    PagePoolExhausted,
    RequestFailed,
    RequestResult,
    RouterOverloaded,
)
