"""Serving fast path: the persistent donated-KV decode engines (serial
per-request DecodeEngine + slot-scheduled continuous-batching
BatchedDecodeEngine)."""

from pytorch_distributed_tpu.serving.engine import (  # noqa: F401
    BatchedDecodeEngine,
    BucketSpec,
    DecodeEngine,
    shim_engine,
)
