"""Serving fast path: the persistent donated-KV decode engine."""

from pytorch_distributed_tpu.serving.engine import (  # noqa: F401
    BucketSpec,
    DecodeEngine,
    shim_engine,
)
