"""SLO priority tiers for the serving engines: the workload vocabulary.

PR-8's scheduler treats every request identically: FIFO admission,
preempt-youngest under page pressure. Real serving traffic is not one
workload — an interactive chat turn and an overnight batch-evaluation
request have different SLOs, and a scheduler that cannot tell them
apart either wastes capacity (provision for batch at interactive p99)
or breaks promises (interactive latency collapses whenever batch
saturates the pool). This module is the tier vocabulary and the
ordering rules; `serving/engine.py` applies them. Everything here is
HOST-SIDE scheduler policy: tiers never reach a traced program, so the
zero-steady-state-compile / strict-donation / rows-invariant-collective
contracts are untouched by construction.

Three classes, ranked (lower rank = higher priority):

- ``INTERACTIVE`` (0) — latency-sensitive. Sorts ahead of everything in
  the admission queue (the "bypass the FIFO head" behaviour), is
  ordered deadline-first WITHIN the tier (earliest deadline admits
  first — the only tier where deadline ordering matters, and the only
  one where reordering is worth deviating from FIFO determinism), and
  may PREEMPT strictly-lower-priority active rows for a slot or for
  pages at admission.
- ``STANDARD`` (1) — the default. Exactly PR-8's behaviour: strict FIFO
  within the tier; an all-STANDARD stream schedules bit-identically to
  the pre-tier engine (regression-pinned).
- ``BATCH`` (2) — throughput traffic. Admits only while the page pool
  has free headroom (``batch_admit_free_frac``), so a batch backlog
  fills otherwise-idle capacity but never bids against interactive
  traffic for a contended pool; first in line for preemption; and its
  rows YIELD to a live interactive row — decode lanes sit the tick out
  (zeroed to the scratch page, so the latency row's tick streams only
  its own pages) and chunk prefills stay out of interactive decode
  ticks. A yielded tick recomputes nothing, so batch tokens stay
  bit-equal their unyielded schedule — delayed, never diverged; batch
  progress resumes the moment no interactive row is live (interactive
  rows retire within ``max_new`` ticks, so the stall is bounded per
  burst — sustained interactive saturation SHOULD starve batch, that
  is the tier's meaning).

Accepted-token accounting under speculation (``speculative_k`` > 0):
every tick commits 1 + accept tokens per row, so tick counts and token
counts diverge — tier math is in TOKENS where it concerns budgets and
deadlines (a row retires after ``max_new`` COMMITTED tokens; deadlines
are wall-clock and care nothing for width) and in TICKS where it
concerns the yield schedule: the bound above tightens to
``ceil(max_new / (1 + mean accepted))`` ticks per interactive burst,
because the latency row itself speculates through the ticks batch sits
out. Yielded batch rows are excluded from drafting entirely (no draft
is computed for a lane that will not dispatch), so yielding under
speculation still recomputes nothing and batch tokens stay bit-equal —
the engines' ``draft_accept`` log events carry the per-commit
drafted/accepted counts the bench aggregates.

Preemption generalizes PR-8's preempt-youngest to
**preempt-lowest-priority-then-youngest**: the victim is the active row
with the MAXIMUM ``(tier_rank, rid)`` — a batch row is preempted before
an interactive row regardless of age, and within a tier the youngest
goes first (PR-8's rule, recovered exactly when every row is STANDARD).
"""

from __future__ import annotations

INTERACTIVE = "interactive"
STANDARD = "standard"
BATCH = "batch"
PRIORITIES = (INTERACTIVE, STANDARD, BATCH)
TIER_RANK = {name: rank for rank, name in enumerate(PRIORITIES)}
TIER_NAME = {rank: name for rank, name in enumerate(PRIORITIES)}


def check_priority(priority: str) -> int:
    """Priority-class name -> tier rank, rejecting unknown classes
    loudly (every submit entry point — engine, router, HTTP 400 — runs
    through here, so the diagnostic is uniform)."""
    rank = TIER_RANK.get(priority)
    if rank is None:
        raise ValueError(
            f"unknown priority class {priority!r}: expected one of "
            f"{PRIORITIES} (lower-latency tiers admit first; 'standard' "
            "is the untier'd default)"
        )
    return rank


def queue_key(tier: int, deadline: float | None, rid: int):
    """Admission-queue sort key: tier rank first, then — INTERACTIVE
    only — earliest deadline, then rid (= submit order). STANDARD/BATCH
    stay strict FIFO within their tier, so an all-default stream keeps
    the exact pre-tier schedule and the fault-resume rid-merge stays
    deterministic."""
    dl = (
        deadline
        if tier == TIER_RANK[INTERACTIVE] and deadline is not None
        else float("inf")
    )
    return (tier, dl, rid)


def preemption_key(tier: int, rid: int):
    """Victim-selection key: the active row with the MAX key is
    preempted first (lowest priority, then youngest)."""
    return (tier, rid)
