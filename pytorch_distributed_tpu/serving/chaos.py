"""Deterministic fault injection for the serving engines.

Robustness claims are only as good as the faults they were tested
against, and real faults (device resets, NaN-producing kernels, lost
RPCs, scheduler stalls) are neither reproducible nor cheap to provoke.
This module makes them both: a ``FaultInjector`` installed on a
``BatchedDecodeEngine`` (``engine.set_fault_injector``) drives seeded,
composable injections through HOST-SIDE hooks only — nothing traced ever
sees it, so injection cannot change a compiled program, its shapes, or
its pinned collective budgets (the whole point: the fault paths must
exercise the SAME executables production runs).

Injection points (the full catalog — docs/ROBUSTNESS.md):

- ``dispatch_error`` — raise before the program runs. The donated cache
  was already taken, so the engine sees exactly what a device-side
  dispatch failure looks like: buffer consumed, in-flight K/V gone.
- ``drop_result``   — raise AFTER the program ran: the compute happened
  and the cache was consumed, but the result never reached the
  scheduler (a lost RPC/transfer). Same recovery path, cost paid.
- ``nan_row``       — flip one active row's non-finite sentinel flag,
  simulating a poisoned logits row at the scheduler boundary (the
  traced sentinel itself is tested separately with genuinely-NaN
  params). Targets decode ticks; transient by default, so the
  quarantine retry succeeds.
- ``slow_tick``     — advance the engine's ``VirtualClock``, modelling a
  stall; this is how deadline expiries are driven deterministically.

Faults come scripted (``Fault(tick=...)`` — exact, for tests) and/or
seeded (per-tick Bernoulli draws from one ``numpy`` generator — for the
soak and the chaos bench leg); both compose. Every firing is counted in
``injector.counts`` so a run can assert its fault schedule actually
fired (a chaos test that injected nothing is coverage theater).
"""

from __future__ import annotations

import dataclasses

import numpy as np

FAULT_KINDS = ("dispatch_error", "drop_result", "nan_row", "slow_tick")


class ChaosDispatchError(RuntimeError):
    """Injected device-side dispatch failure (program never ran; the
    donated cache is consumed regardless)."""


class ChaosDroppedResult(RuntimeError):
    """Injected result loss: the program ran (cache consumed, compute
    paid) but the output never reached the scheduler."""


class VirtualClock:
    """A deterministic engine clock: advances ONLY via ``sleep``/
    ``advance`` (backoff sleeps and slow-tick faults). Pass as both
    ``clock=`` and ``sleep=`` to the engine so deadlines, backoff, and
    stalls replay identically run after run."""

    def __init__(self, start: float = 0.0) -> None:
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self.now += max(0.0, float(seconds))

    advance = sleep


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scripted injection. ``tick`` is the engine's step counter
    (first step = tick 1). ``program`` restricts dispatch faults to
    'prefill' / 'decode_step' (None = first dispatch of the tick);
    ``row`` picks the nan_row target slot (None = seeded choice among
    active rows); ``seconds`` is the slow_tick stall."""

    tick: int
    kind: str
    program: str | None = None
    row: int | None = None
    seconds: float | None = None  # None = injector's slow_tick_s

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {FAULT_KINDS}"
            )


class FaultInjector:
    """Seeded + scripted fault schedule over an engine's dispatch hooks.

    ``faults``: scripted ``Fault`` list (fires exactly once each).
    ``seed``: enables the random schedule — each tick draws one
    Bernoulli per probability from a private generator, so the schedule
    is a pure function of (seed, tick sequence). ``clock``: the engine's
    ``VirtualClock``, required for slow_tick faults.
    """

    def __init__(
        self,
        faults: tuple[Fault, ...] | list[Fault] = (),
        *,
        seed: int | None = None,
        p_dispatch_error: float = 0.0,
        p_drop_result: float = 0.0,
        p_nan_row: float = 0.0,
        p_slow_tick: float = 0.0,
        slow_tick_s: float = 0.25,
        clock: VirtualClock | None = None,
    ) -> None:
        self._scripted: dict[int, list[Fault]] = {}
        for f in faults:
            self._scripted.setdefault(f.tick, []).append(f)
        self._rng = (
            np.random.default_rng(seed) if seed is not None else None
        )
        self._p = {
            "dispatch_error": p_dispatch_error,
            "drop_result": p_drop_result,
            "nan_row": p_nan_row,
            "slow_tick": p_slow_tick,
        }
        self._slow_tick_s = float(slow_tick_s)
        self._clock = clock
        self._engine = None
        self._armed: list[Fault] = []  # this tick's not-yet-fired faults
        self.counts = {k: 0 for k in FAULT_KINDS}

    def install(self, engine) -> "FaultInjector":
        engine.set_fault_injector(self)  # sets our _engine back-reference
        return self

    # -- engine hooks (host-side only) --------------------------------------

    def on_tick(self, tick: int) -> None:
        """Arm this tick's faults (scripted + seeded draws) and apply
        slow_tick stalls immediately."""
        self._armed = list(self._scripted.pop(tick, ()))
        if self._rng is not None:
            for kind, p in self._p.items():
                if p > 0.0 and self._rng.random() < p:
                    self._armed.append(
                        Fault(tick, kind, seconds=self._slow_tick_s)
                    )
        for f in [f for f in self._armed if f.kind == "slow_tick"]:
            self._armed.remove(f)
            if self._clock is None:
                raise ValueError(
                    "slow_tick faults need the engine's VirtualClock "
                    "passed as FaultInjector(clock=...)"
                )
            self._clock.advance(
                self._slow_tick_s if f.seconds is None else f.seconds
            )
            self.counts["slow_tick"] += 1

    def before_dispatch(self, kind: str, tick: int) -> None:
        f = self._pop("dispatch_error", kind)
        if f is not None:
            self.counts["dispatch_error"] += 1
            raise ChaosDispatchError(
                f"injected dispatch failure (tick {tick}, {kind})"
            )

    def after_dispatch(self, kind: str, tick: int, tok, bad):
        f = self._pop("drop_result", kind)
        if f is not None:
            self.counts["drop_result"] += 1
            raise ChaosDroppedResult(
                f"injected result loss (tick {tick}, {kind})"
            )
        if kind == "decode_step":
            f = self._pop("nan_row", kind)
            if f is not None:
                row = f.row
                if row is None:
                    active = [
                        i for i, s in enumerate(self._engine._slots)
                        if s is not None
                    ]
                    if not active:
                        return tok, bad
                    picker = self._rng or np.random.default_rng(tick)
                    row = int(active[picker.integers(len(active))])
                bad = np.asarray(bad).copy()
                bad[row] = True
                self.counts["nan_row"] += 1
        return tok, bad

    # -- internals -----------------------------------------------------------

    def _pop(self, kind: str, program: str) -> Fault | None:
        for f in self._armed:
            if f.kind == kind and f.program in (None, program):
                self._armed.remove(f)
                return f
        return None
