"""Deterministic fault injection for the serving engines.

Robustness claims are only as good as the faults they were tested
against, and real faults (device resets, NaN-producing kernels, lost
RPCs, scheduler stalls) are neither reproducible nor cheap to provoke.
This module makes them both: a ``FaultInjector`` installed on a
``BatchedDecodeEngine`` (``engine.set_fault_injector``) drives seeded,
composable injections through HOST-SIDE hooks only — nothing traced ever
sees it, so injection cannot change a compiled program, its shapes, or
its pinned collective budgets (the whole point: the fault paths must
exercise the SAME executables production runs).

The schedule machinery (scripted + seeded arming, the ``VirtualClock``,
firing counts) is the shared ``utils/chaos.ScriptedFaults`` core — the
training-side injector (train/chaos.py) runs the identical engine with
its own fault catalog and hook points.

Injection points (the full catalog — docs/ROBUSTNESS.md):

- ``dispatch_error`` — raise before the program runs. The donated cache
  was already taken, so the engine sees exactly what a device-side
  dispatch failure looks like: buffer consumed, in-flight K/V gone.
- ``drop_result``   — raise AFTER the program ran: the compute happened
  and the cache was consumed, but the result never reached the
  scheduler (a lost RPC/transfer). Same recovery path, cost paid.
- ``nan_row``       — flip one active row's non-finite sentinel flag,
  simulating a poisoned logits row at the scheduler boundary (the
  traced sentinel itself is tested separately with genuinely-NaN
  params). Targets decode ticks; transient by default, so the
  quarantine retry succeeds.
- ``slow_tick``     — advance the engine's ``VirtualClock``, modelling a
  stall; this is how deadline expiries are driven deterministically.

Faults come scripted (``Fault(tick=...)`` — exact, for tests) and/or
seeded (per-tick Bernoulli draws from one ``numpy`` generator — for the
soak and the chaos bench leg); both compose. Every firing is counted in
``injector.counts`` so a run can assert its fault schedule actually
fired (a chaos test that injected nothing is coverage theater).
"""

from __future__ import annotations

import numpy as np

from pytorch_distributed_tpu.utils.chaos import (  # noqa: F401  (re-export)
    ScriptedFaults,
    VirtualClock,
)
from pytorch_distributed_tpu.utils import chaos as _chaos

FAULT_KINDS = ("dispatch_error", "drop_result", "nan_row", "slow_tick")


class ChaosDispatchError(RuntimeError):
    """Injected device-side dispatch failure (program never ran; the
    donated cache is consumed regardless)."""


class ChaosDroppedResult(RuntimeError):
    """Injected result loss: the program ran (cache consumed, compute
    paid) but the output never reached the scheduler."""


class Fault(_chaos.Fault):
    """One scripted serving injection. ``tick`` is the engine's step
    counter (first step = tick 1). ``program`` restricts dispatch faults
    to 'prefill' / 'decode_step' / 'decode_spec_step' (None = first
    dispatch of the tick);
    ``row`` picks the nan_row target slot (None = seeded choice among
    active rows); ``seconds`` is the slow_tick stall."""

    KINDS = FAULT_KINDS


class FaultInjector(ScriptedFaults):
    """Seeded + scripted fault schedule over an engine's dispatch hooks.

    ``faults``: scripted ``Fault`` list (fires exactly once each).
    ``seed``: enables the random schedule — each tick draws one
    Bernoulli per probability from a private generator, so the schedule
    is a pure function of (seed, tick sequence). ``clock``: the engine's
    ``VirtualClock``, required for slow_tick faults.
    """

    def __init__(
        self,
        faults: tuple[Fault, ...] | list[Fault] = (),
        *,
        seed: int | None = None,
        p_dispatch_error: float = 0.0,
        p_drop_result: float = 0.0,
        p_nan_row: float = 0.0,
        p_slow_tick: float = 0.0,
        slow_tick_s: float = 0.25,
        clock: VirtualClock | None = None,
    ) -> None:
        super().__init__(
            faults,
            seed=seed,
            probabilities={
                "dispatch_error": p_dispatch_error,
                "drop_result": p_drop_result,
                "nan_row": p_nan_row,
                "slow_tick": p_slow_tick,
            },
            slow_kinds=("slow_tick",),
            slow_s=slow_tick_s,
            clock=clock,
            fault_cls=Fault,
        )
        self._engine = None

    def install(self, engine) -> "FaultInjector":
        engine.set_fault_injector(self)  # sets our _engine back-reference
        return self

    # -- engine hooks (host-side only) --------------------------------------

    def before_dispatch(self, kind: str, tick: int) -> None:
        f = self._pop("dispatch_error", kind)
        if f is not None:
            self.counts["dispatch_error"] += 1
            raise ChaosDispatchError(
                f"injected dispatch failure (tick {tick}, {kind})"
            )

    def after_dispatch(self, kind: str, tick: int, tok, bad):
        f = self._pop("drop_result", kind)
        if f is not None:
            self.counts["drop_result"] += 1
            raise ChaosDroppedResult(
                f"injected result loss (tick {tick}, {kind})"
            )
        if kind in ("decode_step", "decode_spec_step"):
            f = self._pop("nan_row", kind)
            if f is not None:
                row = f.row
                if row is None:
                    active = [
                        i for i, s in enumerate(self._engine._slots)
                        if s is not None
                    ]
                    if not active:
                        return tok, bad
                    picker = self._rng or np.random.default_rng(tick)
                    row = int(active[picker.integers(len(active))])
                bad = np.asarray(bad).copy()
                bad[row] = True
                self.counts["nan_row"] += 1
        return tok, bad


ROUTER_FAULT_KINDS = ("replica_kill",)


class RouterFault(_chaos.Fault):
    """One scripted ROUTER-TIER injection. ``tick`` is the router's step
    counter (first step = tick 1); ``row`` picks the target replica id
    (None = seeded choice among the replicas live at fire time)."""

    KINDS = ROUTER_FAULT_KINDS


class RouterFaultInjector(ScriptedFaults):
    """Seeded + scripted replica-death schedule for ``ReplicaRouter``
    (the router-tier storm): a fired ``replica_kill`` makes the router
    treat one replica as a lost PROCESS — no exception from the engine,
    no goodbye; the router's health/failover machinery must notice and
    convert every in-flight request to a re-routed resume entry. Same
    ``utils/chaos.ScriptedFaults`` engine as the per-replica
    ``FaultInjector`` (install THAT on individual replica engines for
    dispatch/NaN/slow faults; brown-out storms combine both), so a whole
    router storm is a pure function of its seeds."""

    def __init__(
        self,
        faults: tuple[RouterFault, ...] | list[RouterFault] = (),
        *,
        seed: int | None = None,
        p_replica_kill: float = 0.0,
        clock: VirtualClock | None = None,
    ) -> None:
        super().__init__(
            faults,
            seed=seed,
            probabilities={"replica_kill": p_replica_kill},
            clock=clock,
            fault_cls=RouterFault,
        )

    def install(self, router) -> "RouterFaultInjector":
        router.set_fault_injector(self)
        return self

    def pop_kill(self, live_ids) -> int | None:
        """The replica to kill this tick, or None. Scripted faults may
        pin the target (``row``); seeded draws pick uniformly among the
        replicas live at fire time (a kill schedule drawn blind could
        only ever miss). A fault whose pinned target is already down is
        consumed without effect — the process it models is already
        dead."""
        f = self._pop("replica_kill", None)
        if f is None:
            return None
        live_ids = list(live_ids)
        if f.row is not None:
            if f.row not in live_ids:
                return None
            self._count("replica_kill")
            return int(f.row)
        if not live_ids:
            return None
        if self._rng is None:
            # Unseeded scripted faults still need an ADVANCING generator
            # for target choice — a fresh rng per call would pin every
            # kill to the same pick.
            self._rng = np.random.default_rng(0)
        self._count("replica_kill")
        return int(live_ids[self._rng.integers(len(live_ids))])
