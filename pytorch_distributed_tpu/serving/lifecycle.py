"""Request lifecycle vocabulary for the serving engines.

Every request submitted to ``BatchedDecodeEngine`` ends in exactly one
TERMINAL state, delivered as a ``RequestResult`` through ``pop_result``:

- ``DONE``    — ran to its token budget (or per-row EOS); ``tokens`` is
  the full prompt + generated sequence.
- ``FAILED``  — the engine gave up on it: non-finite logits persisted
  after the one fresh-row quarantine retry, or the request exhausted its
  fault-resume budget (``request_retries``). ``tokens`` holds the clean
  partial prefix generated before the fault.
- ``ABORTED`` — the client called ``abort(rid)``; partial prefix.
- ``EXPIRED`` — its deadline (``submit(timeout_s=...)``) passed while
  queued or mid-decode; partial prefix.

The state machine (docs/ROBUSTNESS.md draws it):

    submit -> QUEUED -> ACTIVE -> DONE
                 |         |----> ABORTED / EXPIRED / FAILED
                 |         '----> QUEUED (fault resume: NaN quarantine,
                 |                dispatch failure, engine replay)
                 '------> ABORTED / EXPIRED

Non-terminal states (QUEUED/ACTIVE) are engine-internal — observable via
``queued_rids()`` / ``active_rids()`` — and a request may bounce
ACTIVE -> QUEUED any number of times through the fault-resume path; the
invariant the soak asserts is that every rid reaches exactly ONE terminal
result, and a terminal rid never reappears.

The paged engine (``PagedBatchedDecodeEngine``) adds one more
ACTIVE -> QUEUED bounce: PREEMPTION. When the KV page pool is exhausted
mid-decode, the youngest active request (the one "queued last") is
converted to a resume entry — clean tokens-so-far preserved, pages
released — and re-admitted when pages free up, continuing
token-identically. Preemption is load shedding, not a fault: it charges
no retry budget and cannot FAIL a request. The lifecycle log records it
as a ``preempt`` event next to ``submit``/``admit``/``retire``, and
paged admissions log their prefix-cache outcome (``prefix_hit`` with the
shared token count) so cache effectiveness is visible per request.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

DONE = "DONE"
FAILED = "FAILED"
ABORTED = "ABORTED"
EXPIRED = "EXPIRED"
TERMINAL_STATES = (DONE, FAILED, ABORTED, EXPIRED)


@dataclasses.dataclass
class RequestResult:
    """One request's terminal outcome. ``tokens`` always holds the
    original prompt followed by every CLEAN token generated before the
    terminal transition — for non-DONE states that is a prefix of what an
    undisturbed run would have produced (quarantined/garbage tokens are
    never appended), so partial results are usable, not corrupt."""

    rid: int
    state: str  # one of TERMINAL_STATES
    tokens: np.ndarray  # [prompt + generated-so-far] int32
    reason: str = ""  # diagnostic for FAILED/ABORTED/EXPIRED

    def __post_init__(self) -> None:
        if self.state not in TERMINAL_STATES:
            raise ValueError(
                f"state must be one of {TERMINAL_STATES}, got {self.state!r}"
            )


@dataclasses.dataclass
class EngineSnapshot:
    """Host-side engine state for crash recovery: everything needed to
    rebuild a ``BatchedDecodeEngine`` after the device (and with it the
    donated KV cache) is lost. In-flight rows are captured as RESUME
    entries carrying their tokens-so-far; a rebuilt engine re-prefills
    each from that prefix and continues token-identically (the per-row
    PRNG fold schedule is part of the entry). Capture between ``step``
    calls; restore onto a fresh idle engine of the same model config."""

    pending: list  # engine._Pending entries, ascending rid
    next_rid: int
    results: dict[int, RequestResult]  # undelivered terminal results
    stats: dict[str, Any] = dataclasses.field(default_factory=dict)


class AdmissionQueueFull(RuntimeError):
    """Bounded admission queue overflow under the ``reject`` backpressure
    policy (or ``block`` timing out): submitted load exceeds what the
    engine drains. Carries the limit in the message so the 429 path is
    diagnosable."""


class RequestFailed(RuntimeError):
    """The serial ``DecodeEngine`` detected non-finite logits and the one
    fresh-cache retry reproduced them — the request's output would be
    garbage, so it fails loudly instead of emitting tokens."""


class PagePoolExhausted(RuntimeError):
    """The paged engine could not free a KV page even after preempting
    every other active request — an invariant violation (construction
    validates ``pool_pages >= max_len/page_size + 1``, which guarantees
    one full-length row always fits), kept as a loud defensive raise
    instead of the silent hang a starved allocator would otherwise be."""


class DispatchFailure(RuntimeError):
    """The batched engine's consecutive-dispatch-failure budget
    (``dispatch_retries``) is exhausted. Engine state is CONSISTENT when
    this raises: every in-flight request has been requeued (or FAILED if
    out of resume budget) and the cache dropped — the caller can
    ``snapshot()`` and rebuild, or keep the engine and try again later."""


class RouterOverloaded(RuntimeError):
    """SLO-aware load shedding (`serving/router.py`): every routable
    replica is past its admission thresholds (queue depth and/or page
    headroom), so the router rejects LOUDLY instead of queueing without
    bound — unbounded queues turn overload into unbounded p99, which is
    worse than a clean 429. ``retry_after_s`` is the router's drain-time
    estimate; the HTTP front door maps it onto a ``Retry-After``
    header."""

    def __init__(self, message: str, *, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s
