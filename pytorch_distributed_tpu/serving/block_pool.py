"""Host-side KV page-pool bookkeeping for the paged serving engine.

The paged ``BatchedDecodeEngine`` variant (serving/engine.py:
``PagedBatchedDecodeEngine``) stores K/V in a flat pool of fixed-size
PAGES — ``[L, pool_pages, page_size, Hkv, D]`` on device — and gives each
request a per-row BLOCK TABLE of page ids instead of a dedicated
``max_len`` cache row. This module is the pool's host-side brain; nothing
here is traced (the device only ever sees page-id int32 operands), so
allocation policy can never recompile a program or perturb a pinned
budget.

Three responsibilities:

1. **Allocation + refcounts.** Pages are acquired per row and REFERENCE
   COUNTED, because prefix sharing hands the same physical page to many
   rows. A page returns to the free list only when its last reference
   drops AND it is not retained by the prefix cache.

2. **Prefix cache.** Identical prompt prefixes — the shared system
   prompts real traffic repeats millions of times — are stored ONCE:
   prefixes are keyed by a sha1 CHAIN over fixed-size token chunks
   (``key_j = sha1(key_{j-1} || tokens[jC:(j+1)C])``), so a chunk's key
   commits to the ENTIRE prefix before it, which is exactly the
   precondition that makes K/V sharing sound (a position's K/V is a pure
   function of the tokens at and before it — causal attention never
   looks right). ``match_prefix`` walks the chain and hands back shared
   pages (acquiring a reference on each); ``register_chunk`` publishes a
   freshly prefilled chunk's pages for future requests. Chunks are
   retained after their last reference drops (that is the cache) and
   EVICTED in LRU order only when allocation would otherwise fail — so
   a hot system prompt stays resident across requests that never
   overlap in time.

3. **Copy-on-write discipline, by construction.** Shared pages are never
   written: sharing is chunk-granular over the prefill prefix, a row's
   own writes start at its first un-cached position (always a chunk
   boundary), and decode writes land past the prompt — so two rows that
   share a prefix and then fork diverge onto PRIVATE pages without any
   device-side copy (the "copy" in copy-on-write never happens; the
   write simply goes to a fresh page). tests/test_serving_paged.py pins
   the fork case.

Page id 0 is RESERVED as the scratch page: block-table entries default
to 0, so free/garbage rows in the oblivious decode dispatch write and
read page 0 — which no live row's table ever points at. (Concurrent
garbage writes to the scratch page are racy-by-design; nothing reads
them, same as the dense engine's free-row rows.)
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np


@dataclasses.dataclass
class _CachedChunk:
    """One published prefix chunk: the pages holding its K/V."""

    pids: list  # page ids, in position order


class BlockPool:
    """Fixed-size page pool with refcounts and a chunk-chained prefix
    cache. Page ids are ``1..pool_pages-1`` (0 is the scratch page).
    Purely host-side state; see the module docstring."""

    def __init__(
        self, pool_pages: int, page_size: int, chunk_tokens: int
    ) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if chunk_tokens < page_size or chunk_tokens % page_size:
            raise ValueError(
                f"chunk_tokens ({chunk_tokens}) must be a positive "
                f"multiple of page_size ({page_size})"
            )
        if pool_pages < 2:
            raise ValueError(
                f"pool_pages must be >= 2 (page 0 is the reserved "
                f"scratch page), got {pool_pages}"
            )
        self.pool_pages = int(pool_pages)
        self.page_size = int(page_size)
        self.chunk_tokens = int(chunk_tokens)
        # Ascending allocation order (pop from the front via index) is
        # deterministic and makes tests legible.
        self._free: list[int] = list(range(1, pool_pages))
        self._ref: dict[int, int] = {}
        # Insertion-ordered = LRU order; match_prefix refreshes recency.
        self._cache: dict[str, _CachedChunk] = {}
        self._cached_pages: set[int] = set()
        # Chunk keys PINNED against LRU eviction (session-aware
        # retention, serving/session.py): a live chat session's prefix
        # chunks stay resident between turns even under allocation
        # pressure — the pin, not recency, is what keeps turn N+1's
        # prefill ~one chunk. Bounded by the engine's pin budget.
        # REFCOUNTED per key: two sessions sharing a system-prompt
        # prefix pin the same chunks, and one closing must not strip
        # the survivor's retention.
        self._pinned: dict[str, int] = {}
        self.stats: dict[str, int] = {
            "prefix_queries": 0,
            "prefix_hits": 0,
            "prefix_hit_tokens": 0,
            "evictions": 0,
            "peak_pages_in_use": 0,
            # Disaggregated serving's kv_handoff traffic through THIS
            # pool: pages landed by import_handoff / released by
            # complete_handoff (engine.py) — the page-level ledger the
            # handoff-bytes figures in serving_disagg_bench.json roll
            # up from.
            "handoff_pages_in": 0,
            "handoff_pages_out": 0,
        }

    # -- accounting --------------------------------------------------------

    def free_pages(self) -> int:
        """Pages immediately allocatable WITHOUT evicting cached
        prefixes (the conservative headroom figure ``engine.stats()``
        reports; eviction can stretch it by the unreferenced cached
        pages)."""
        return len(self._free)

    def pages_in_use(self) -> int:
        """Pages referenced by at least one live row (the working set —
        what ``decode_bench`` reports as cache HBM actually in use)."""
        return sum(1 for r in self._ref.values() if r > 0)

    def pages_resident(self) -> int:
        """Pages holding content (referenced OR retained by the prefix
        cache) — everything not on the free list."""
        return self.pool_pages - 1 - len(self._free)

    def allocatable_pages(self) -> int:
        """Pages the allocator can actually deliver: immediately free
        plus whole cached-and-unpinned chunks no live row references —
        exactly what LRU eviction reclaims on demand (``_evictable``'s
        rule). The BATCH admission gate reads THIS, not ``free_pages``:
        a pool idling full of retired prefixes is headroom, not
        pressure — only live working sets and session pins subtract."""
        evictable = sum(
            len(chunk.pids)
            for key, chunk in self._cache.items()
            if key not in self._pinned
            and all(self._ref.get(p, 0) == 0 for p in chunk.pids)
        )
        return len(self._free) + evictable

    def pinned_pages(self) -> int:
        """Pages held ONLY by a pin: in pinned chunks and not currently
        referenced by any live row. This is the capacity a pin takes
        away from the allocator beyond the working set (``pages_in_use``
        already counts referenced pages), so it is the figure
        ``engine.stats()`` reports and the router's least-loaded scoring
        adds to page pressure — a session-heavy replica looks loaded
        BEFORE it starts preempting for its pinned residents."""
        return sum(
            1
            for key in self._pinned
            if key in self._cache
            for pid in self._cache[key].pids
            if self._ref.get(pid, 0) == 0
        )

    def refcount(self, pid: int) -> int:
        """Current reference count of one page (0 = free or cache-
        retained only). Introspection for the sharing pins: the
        speculative-rollback tests read it to prove a shared prefix
        page stays multiply-referenced — and byte-untouched — while a
        borrowing row speculates past it."""
        return self._ref.get(pid, 0)

    def cached_page_ids(self) -> set[int]:
        """Page ids currently retained by the prefix cache (a copy).
        The COW/rollback pins snapshot these pages' device content
        around a speculating neighbour's run."""
        return set(self._cached_pages)

    def pin(self, keys) -> None:
        """Protect cached chunks from LRU eviction (unknown keys are
        ignored — a chunk can lose the first-writer race or die with a
        pool reset before its pin lands). Pins are REFCOUNTED: each
        holder unpins exactly what it pinned, and the chunk returns to
        LRU only when the last holder lets go."""
        for key in keys:
            if key in self._cache:
                self._pinned[key] = self._pinned.get(key, 0) + 1

    def unpin(self, keys) -> None:
        """Release one holder's pins (idempotent for keys whose pin
        never landed); a chunk returns to ordinary LRU retention when
        its last holder unpins."""
        for key in keys:
            n = self._pinned.get(key)
            if n is None:
                continue
            if n <= 1:
                del self._pinned[key]
            else:
                self._pinned[key] = n - 1

    def _bump_peak(self) -> None:
        n = self.pages_in_use()
        if n > self.stats["peak_pages_in_use"]:
            self.stats["peak_pages_in_use"] = n

    # -- allocation --------------------------------------------------------

    def alloc(self, n: int) -> list[int] | None:
        """Take ``n`` fresh private pages (refcount 1 each), evicting
        unreferenced cached chunks LRU-first if the free list runs dry.
        Returns None — with the pool UNCHANGED — when even eviction
        cannot cover the request (the caller preempts or defers)."""
        if n == 0:
            return []
        evicted: list[str] = []
        while len(self._free) < n:
            key = self._evictable()
            if key is None:
                # Roll back nothing: eviction only ever freed pages,
                # which is harmless to keep; the allocation itself never
                # started.
                return None
            evicted.append(key)
            self._evict(key)
        out = self._free[:n]
        del self._free[:n]
        for pid in out:
            self._ref[pid] = 1
        self._bump_peak()
        return out

    def alloc_for_handoff(self, n: int) -> list[int] | None:
        """``alloc`` for a kv_handoff import: same allocator, same
        None-on-exhaustion contract, plus the handoff page ledger the
        disaggregation bench reports."""
        pids = self.alloc(n)
        if pids is not None:
            self.stats["handoff_pages_in"] += n
        return pids

    def note_handoff_out(self, n: int) -> None:
        """Count a completed export's pages (released by the engine's
        ``complete_handoff`` through the normal ``release`` path)."""
        self.stats["handoff_pages_out"] += n

    def _evictable(self) -> str | None:
        for key, chunk in self._cache.items():  # LRU-first
            if key in self._pinned:
                continue  # session-pinned: survives pressure
            if all(self._ref.get(p, 0) == 0 for p in chunk.pids):
                return key
        return None

    def _evict(self, key: str) -> None:
        chunk = self._cache.pop(key)
        self.stats["evictions"] += 1
        for pid in chunk.pids:
            self._cached_pages.discard(pid)
            self._ref.pop(pid, None)
            self._free.append(pid)

    def acquire(self, pids) -> None:
        """Add one reference to each page (prefix sharing)."""
        for pid in pids:
            self._ref[pid] = self._ref.get(pid, 0) + 1
        self._bump_peak()

    def release(self, pids) -> None:
        """Drop one reference per page. A page at refcount 0 returns to
        the free list UNLESS the prefix cache retains it (then it stays
        resident, evictable-on-demand)."""
        for pid in pids:
            r = self._ref.get(pid, 0) - 1
            if r < 0:
                raise RuntimeError(
                    f"page {pid} released more times than acquired — "
                    "engine bookkeeping bug"
                )
            self._ref[pid] = r
            if r == 0 and pid not in self._cached_pages:
                self._ref.pop(pid)
                self._free.append(pid)

    # -- prefix cache ------------------------------------------------------

    def _chain_digest(self, prev: str, tokens: np.ndarray) -> str:
        h = hashlib.sha1()
        h.update(prev.encode())
        h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
        return h.hexdigest()

    def chain_keys(self, tokens: np.ndarray, length: int) -> list[str]:
        """The chain keys of every full chunk covering
        ``tokens[:length]`` (length floored to a chunk multiple) — what
        session retention pins. Pure digests: no cache reads, no
        references taken."""
        c = self.chunk_tokens
        key, keys = "", []
        for start in range(0, (int(length) // c) * c, c):
            key = self._chain_digest(key, tokens[start:start + c])
            keys.append(key)
        return keys

    def match_prefix(
        self, tokens: np.ndarray, max_tokens: int
    ) -> tuple[int, list[int], str]:
        """Longest cached chunk-chain prefix of ``tokens``, capped at
        ``max_tokens`` (callers cap at len-1 so at least one token is
        left to prefill — the next-token logits have to come from
        somewhere). Returns (cached_len, shared page ids, chain key at
        cached_len) with one reference ACQUIRED per shared page;
        cached_len is always a multiple of chunk_tokens. Carry the
        returned key into ``register_chunk(prev_key=...)`` so publishing
        stays one digest per chunk instead of a from-zero rewalk."""
        c = self.chunk_tokens
        self.stats["prefix_queries"] += 1
        limit = (max(0, int(max_tokens)) // c) * c
        key = ""
        pids: list[int] = []
        length = 0
        while length + c <= limit:
            nxt = self._chain_digest(key, tokens[length:length + c])
            chunk = self._cache.get(nxt)
            if chunk is None:
                break
            # LRU refresh: re-insert at the back.
            self._cache.pop(nxt)
            self._cache[nxt] = chunk
            key = nxt
            pids += chunk.pids
            length += c
        if length:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_hit_tokens"] += length
            self.acquire(pids)
        return length, pids, key

    def cancel_match(self, length: int, pids) -> None:
        """Undo a ``match_prefix`` whose admission could not proceed
        (page starvation deferred the request): drop the acquired
        references AND the stats it counted — a head-of-line request
        retrying every tick must not inflate the hit counters the bench
        commits (each retry will re-match when it finally admits)."""
        self.release(pids)
        self.stats["prefix_queries"] -= 1
        if length:
            self.stats["prefix_hits"] -= 1
            self.stats["prefix_hit_tokens"] -= length

    def register_chunk(
        self, tokens: np.ndarray, start: int, pids,
        prev_key: str | None = None,
    ) -> str:
        """Publish the chunk covering ``tokens[start : start+chunk]``
        (its K/V now lives in ``pids``) for future ``match_prefix``
        hits. ``start`` must be chunk-aligned. ``prev_key`` is the chain
        key at ``start`` (from ``match_prefix`` or the previous
        ``register_chunk`` — ONE digest per publish); None falls back to
        rewalking the chain from token 0. First writer wins: an already
        published identical chunk keeps its pages and the duplicate
        stays private to its row. Returns the chunk's chain key (carry
        it forward as the next ``prev_key``)."""
        c = self.chunk_tokens
        if start % c:
            raise ValueError(
                f"register_chunk start {start} is not chunk-aligned "
                f"(chunk_tokens={c})"
            )
        if prev_key is None:
            prev_key = ""
            for j in range(0, start, c):
                prev_key = self._chain_digest(prev_key, tokens[j:j + c])
        key = self._chain_digest(prev_key, tokens[start:start + c])
        if key not in self._cache:
            self._cache[key] = _CachedChunk(pids=list(pids))
            self._cached_pages.update(pids)
        return key

    # -- lifecycle ---------------------------------------------------------

    def reset(self) -> None:
        """Drop EVERYTHING (free all pages, forget the prefix cache):
        the recovery path after a failed dispatch consumed the donated
        pool buffer — its content is gone, so any cached chunk would
        alias garbage."""
        self._free = list(range(1, self.pool_pages))
        self._ref.clear()
        self._cache.clear()
        self._cached_pages.clear()
        self._pinned.clear()
