"""Multi-turn chat sessions over the paged engine's prefix cache.

A chat session's turn N resubmits the conversation-so-far plus one new
user message. Without help, that is a full prefill per turn — O(turns²)
prefill cost over a conversation. The PR-8 machinery already contains
the fix: the sha1 chunk-chained prefix cache stores K/V per token-chunk,
so if turn N-1's pages are still resident when turn N arrives, the
whole recorded transcript matches and turn N prefills ~one chunk (the
new user message plus the unaligned tail). This module is the
host-side session brain that makes "still resident" a contract instead
of a hope:

- **Transcript recording**: each session records the full token
  sequence it has served (prompt + generated, updated on DONE). A
  turn's prompt must EXTEND the recorded transcript exactly — a
  resubmission whose history diverges is rejected loudly naming the
  first divergent position, because a diverged history would silently
  serve the new turn against the OLD cached K/V (the tokens the client
  sent would not be the tokens attended to).
- **Turn-over-turn publishing**: a one-shot request only publishes
  prefill chunks (decode-written pages die with the row). A session
  row additionally publishes its full DECODE-written chunks at
  retirement — the K/V of a generated token is the same pure function
  of its prefix, so the chunks are sound cache entries — which is what
  lets turn N+1 skip re-prefilling turn N's reply.
- **Pinning with a budget**: published session chunks are PINNED
  against LRU eviction (serving/block_pool.py) while the session
  lives, bounded by ``pin_budget_pages``. Over budget, the
  longest-idle session is evicted LOUDLY (``session_evict`` log event
  + counter): its chunks return to ordinary LRU (possibly still
  hittable), its transcript survives, and its next turn simply pays
  the prefill a cold cache costs. Pins can also be broken by the
  engine under page starvation — retention must never deadlock
  allocation.

Nothing here is traced, and nothing here touches device state: the
tracker is pure scheduler bookkeeping over the block pool, so sessions
cannot recompile a program or move a pinned budget. One tracker per
paged engine; the router keeps its own client-key -> (replica, sid)
stickiness map and re-opens sessions on a survivor after failover
(transcript-carrying resubmission makes that lossless — the new
replica just pays a cold prefill).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pytorch_distributed_tpu.utils.logging import log_event


@dataclasses.dataclass
class _Session:
    sid: int
    transcript: np.ndarray  # every token served so far ([0] at open)
    pinned_keys: list  # chunk chain keys currently pinned for this sid
    inflight_rid: int | None = None  # one outstanding turn at a time
    last_active: float = 0.0  # engine clock; idle-eviction order
    turns: int = 0


class SessionTracker:
    """Host-side session registry for one ``PagedBatchedDecodeEngine``
    (the engine constructs and drives it; see the engine's
    ``open_session`` / ``submit(session=)`` / ``close_session``)."""

    def __init__(self, pool, *, pin_budget_pages: int, clock) -> None:
        if pin_budget_pages < 0:
            raise ValueError(
                f"pin_budget_pages must be >= 0, got {pin_budget_pages}"
            )
        self.pool = pool
        self.pin_budget_pages = int(pin_budget_pages)
        self._clock = clock
        self._sessions: dict[int, _Session] = {}
        self._next_sid = 0
        self._inflight: dict[int, int] = {}  # rid -> sid
        # Turn-N (N >= 2) prefill economics: tokens the client RESENT
        # (the recorded transcript) vs tokens the prefix cache actually
        # served — the hit-rate figure the scenarios bench pins >= 0.9.
        self.hit = {"resubmitted_tokens": 0, "cached_tokens": 0}
        self._hit_counted: set[int] = set()
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._sessions)

    @property
    def chunk_pages(self) -> int:
        return self.pool.chunk_tokens // self.pool.page_size

    def pinned_pages_total(self) -> int:
        """Pages held by session pins (budget accounting: every pinned
        chunk is chunk_pages pages, referenced or not). DISTINCT chunks
        only — two sessions sharing a system-prompt prefix pin the same
        physical pages once, and the budget charges what the pool
        actually holds, not per-holder."""
        keys: set = set()
        for s in self._sessions.values():
            keys.update(s.pinned_keys)
        return len(keys) * self.chunk_pages

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> int:
        sid = self._next_sid
        self._next_sid += 1
        self._sessions[sid] = _Session(
            sid=sid, transcript=np.zeros((0,), np.int32),
            pinned_keys=[], last_active=self._clock(),
        )
        log_event("session_open", session=sid, t=round(self._clock(), 6))
        return sid

    def close(self, sid: int) -> None:
        s = self._sessions.pop(sid, None)
        if s is None:
            raise ValueError(f"unknown session id {sid}")
        if s.inflight_rid is not None:
            self._inflight.pop(s.inflight_rid, None)
        self.pool.unpin(s.pinned_keys)
        log_event(
            "session_close", session=sid, turns=s.turns,
            t=round(self._clock(), 6),
        )

    def check_turn(self, sid: int, prompt: np.ndarray) -> int:
        """Validate one turn submission; returns the resubmitted-prefix
        length (= recorded transcript length). Loud on: unknown sid, a
        still-inflight previous turn, and a prompt whose history
        diverges from (or fails to extend) the transcript."""
        s = self._sessions.get(sid)
        if s is None:
            raise ValueError(
                f"unknown session id {sid}: open_session() first (or "
                "the session was closed/evicted)"
            )
        if s.inflight_rid is not None:
            raise ValueError(
                f"session {sid} already has turn rid "
                f"{s.inflight_rid} in flight — one outstanding turn "
                "per session (pop its result first; interleaved turns "
                "would race the transcript)"
            )
        tr = s.transcript
        if prompt.shape[0] <= tr.shape[0]:
            raise ValueError(
                f"session {sid} turn must EXTEND the recorded "
                f"transcript ({tr.shape[0]} tokens) with at least one "
                f"new token; got a {prompt.shape[0]}-token prompt — "
                "resubmit the conversation-so-far plus the new message"
            )
        head = prompt[: tr.shape[0]]
        if not np.array_equal(head, tr):
            at = int(np.argmax(head != tr))
            raise ValueError(
                f"session {sid} resubmission diverges from the "
                f"recorded transcript at position {at} (sent token "
                f"{int(head[at])}, transcript has {int(tr[at])}): the "
                "cached K/V no longer matches the client's history — "
                "open a fresh session for an edited conversation"
            )
        return int(tr.shape[0])

    def begin_turn(self, sid: int, rid: int) -> None:
        s = self._sessions[sid]
        s.inflight_rid = rid
        s.turns += 1
        s.last_active = self._clock()
        self._inflight[rid] = sid
        log_event(
            "session_turn", session=sid, rid=rid, turn=s.turns,
            transcript=int(s.transcript.shape[0]),
            t=round(self._clock(), 6),
        )

    def on_terminal(self, rid: int) -> None:
        """Any terminal state clears the in-flight marker (the DONE
        path updated the transcript first via ``on_turn_done``); a
        FAILED/EXPIRED/ABORTED turn leaves the transcript unchanged, so
        the client's retry of the same turn still extends it."""
        self._hit_counted.discard(rid)
        sid = self._inflight.pop(rid, None)
        if sid is None:
            return
        s = self._sessions.get(sid)
        if s is not None and s.inflight_rid == rid:
            s.inflight_rid = None
            s.last_active = self._clock()

    # -- retention ----------------------------------------------------------

    def on_turn_done(self, sid: int, transcript: np.ndarray,
                     keys: list) -> None:
        """A session turn retired DONE: record the new transcript and
        pin its chunk keys, evicting longest-idle sessions (never this
        one) while over the pin budget. ``keys`` is the full chain from
        token 0 — pins are idempotent per key."""
        s = self._sessions.get(sid)
        if s is None:
            return  # closed/evicted mid-turn, or a restored foreign rid
        s.transcript = np.asarray(transcript, np.int32)
        s.last_active = self._clock()
        new = [k for k in keys if k not in s.pinned_keys]
        self.pool.pin(new)
        s.pinned_keys.extend(new)
        while (
            self.pinned_pages_total() > self.pin_budget_pages
            and self.evict_idle(exclude_sid=sid)
        ):
            pass
        if self.pinned_pages_total() > self.pin_budget_pages:
            # Still over budget (this session alone exceeds it, or the
            # other pinners are all mid-turn and unevictable): shed this
            # session's TAIL pins — the chain matches from the front, so
            # keeping the head preserves the longest matchable prefix.
            # The overage can exceed OUR pin count when inflight
            # neighbours hold the rest; clamp — their pins release at
            # their own turn end, which re-runs this balance.
            over = (
                self.pinned_pages_total() - self.pin_budget_pages
                + self.chunk_pages - 1
            ) // self.chunk_pages
            over = min(over, len(s.pinned_keys))
            if over:
                drop = s.pinned_keys[len(s.pinned_keys) - over:]
                s.pinned_keys = s.pinned_keys[: len(s.pinned_keys) - over]
                self.pool.unpin(drop)
                log_event(
                    "session_evict", session=sid, partial=True,
                    unpinned_chunks=len(drop), t=round(self._clock(), 6),
                )

    def evict_idle(self, exclude_sid: int | None = None) -> bool:
        """Unpin the longest-idle session with no turn in flight (LOUD:
        ``session_evict``). The session record and transcript survive —
        only the retention guarantee is lost; its next turn pays
        whatever the LRU left behind. Returns False when nothing is
        evictable (everything pinned is mid-turn)."""
        cands = [
            s for s in self._sessions.values()
            if s.pinned_keys and s.inflight_rid is None
            and s.sid != exclude_sid
        ]
        if not cands:
            return False
        victim = min(cands, key=lambda s: (s.last_active, s.sid))
        self.pool.unpin(victim.pinned_keys)
        n = len(victim.pinned_keys)
        victim.pinned_keys = []
        self.evictions += 1
        log_event(
            "session_evict", session=victim.sid, unpinned_chunks=n,
            t=round(self._clock(), 6),
        )
        return True

    def on_pool_reset(self) -> None:
        """The donated pool was consumed by a failed dispatch and the
        block pool reset: every pinned chunk's content is gone, so the
        pins are dropped (transcripts survive — the next turn re-pays
        prefill, exactly like the fault model's other resume paths)."""
        for s in self._sessions.values():
            s.pinned_keys = []

    # -- accounting ---------------------------------------------------------

    def note_admit(self, rid: int, cached: int, resub_len: int) -> None:
        """First admission of a session turn with a non-empty recorded
        transcript: account how much of the RESENT history the prefix
        cache served (preemption re-admissions are not re-counted — the
        economics of the turn were decided at first admission)."""
        if resub_len <= 0 or rid in self._hit_counted:
            return
        self._hit_counted.add(rid)
        self.hit["resubmitted_tokens"] += int(resub_len)
        self.hit["cached_tokens"] += min(int(cached), int(resub_len))

    def hit_rate(self) -> float:
        """cached/resubmitted over every turn >= 2 — the scenarios
        bench's pinned figure."""
        return self.hit["cached_tokens"] / max(
            1, self.hit["resubmitted_tokens"]
        )
